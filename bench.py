"""Benchmark: Schedule() rounds at cluster scale on real hardware.

North-star target (BASELINE.md): 10k machines / 100k pending pods per
round in < 1 s with placement-cost parity vs the exact oracle.  The
reference publishes no numbers of its own (its default round *interval*
is 10 s, pkg/config/config.go:120); the 1 s round target is the baseline
``vs_baseline`` is computed against (>1.0 = beating it).

Structure: a scale LADDER run NORTH-STAR-FIRST (10k machines, then
1k -> 2k -> 4k for the scaling table; 10 pods per machine).  Every rung
runs in a subprocess with a timeout, so a worker crash or a wedged
accelerator tunnel degrades the report instead of zeroing it — the
parent process never touches jax and ALWAYS emits the final JSON line.
The backend is probed ONCE, in the parent, before any child runs: a dead
tunnel costs one probe timeout for the whole bench, not one per child,
and the verdict (live accelerator, or latched clean-CPU environment) is
exported to every child via POSEIDON_BENCH_NO_PROBE (round-4 review: 7
children x 300 s of re-probing a known-dead tunnel consumed the outer
budget that the 10k/100k rung needed).  On a live backend the parent
holds the host-wide device flock for the whole run; children inherit
serialization by running sequentially under it.

Three honest numbers per rung (round-2 review: a drain-and-resubmit-
identical wave measures only the bit-identical warm cache):

- ``cold_s``: the very first round, XLA compile included.  Children
  share a persistent compilation cache (so the 2k rung reuses shapes the
  1k rung compiled, and repeat bench runs start warm); each rung reports
  ``cache_warm`` so a cache-hit cold_s is never mistaken for a true
  first-compile number;
- ``wave_p50_s``: full-wave rounds — every task pending at once — where
  each wave is a FRESH random task population (new shapes, new EC ids),
  so nothing is bit-identical round to round;
- ``churn_p50_s``: steady-state rounds with 1% of tasks replaced.

Plus ``parity_ok``: the TPU solver's objective equals the exact host
oracle (networkx network simplex) on the 100-node/1k-pod BASELINE
config 1 instance.

Prints ONE JSON line PER COMPLETED STAGE (each a superset of the
previous; consumers take the LAST line — this way a kill at any point
still leaves a valid, maximal artifact on stdout)::

  {"metric": "schedule_round_s", "value": <wave p50 s>, "unit": "s",
   "vs_baseline": <1.0/value>, "machines": ..., "tasks": ...,
   "cold_s": ..., "wave_p50_s": ..., "churn_p50_s": ...,
   "parity_ok": true, "trace": {...config-5 replay...},
   "ladder": [...per-rung results/errors...]}

``value`` is the fresh-population WAVE p50 at the NORTH-STAR config
(10k machines / 100k pods pending at once) and ONLY that config: a
missing or unconverged 10k rung posts ``vs_baseline: 0`` (round-4
review: "largest completed rung" scoring let a bench that timed out
earlier post a better-looking score than an honest 10k completion).
``churn_p50_s`` reports the steady-state latency alongside it and
``restart_s`` the recovery-to-first-placement after a checkpoint
restore at the same scale.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

from poseidon_tpu.utils.hatches import hatch_flag, hatch_float, hatch_int

# North-star config FIRST: any budget squeeze (wedged tunnel, slow
# backend, outer deadline) must cost the scaling-table rungs, never the
# scored 10k/100k number (round-4 review: the ascending ladder made the
# north-star rung the first casualty of every timeout).
NORTH_STAR = (10_000, 100_000)
LADDER = [NORTH_STAR, (1_000, 10_000), (2_000, 20_000), (4_000, 40_000)]
PARITY_TIMEOUT_S = 600


def rung_timeout_s() -> int:
    """Per-rung child budget — read at call time (the hatch registry's
    import-time-read discipline: a wrapper exporting the knob after
    this module loads must still be honored)."""
    return hatch_int("POSEIDON_BENCH_RUNG_TIMEOUT")


def features_timeout_s() -> int:
    """BASELINE configs 2-4 (selectors/affinity/gang) run at the
    north-star scale (10k machines, ~45 s warm + compile headroom);
    cluster scale needs more than the parity budget."""
    return hatch_int("POSEIDON_BENCH_FEATURES_TIMEOUT")


def term_grace_s() -> int:
    """Grace between SIGTERM and SIGKILL for a timed-out child: the
    child's SIGTERM handler (install_graceful_term) exits after the
    in-flight device op returns, so the grace must cover one
    worst-case device program.  SIGKILL is the very last resort —
    killing a chip-holding process mid-op wedges the tunnel for
    everyone."""
    return hatch_int("POSEIDON_BENCH_TERM_GRACE")



def _prework_allowance() -> int:
    """Extra child budget for device-lock wait + backend probe.

    Zero once a probe verdict is latched (POSEIDON_BENCH_NO_PROBE set by
    the parent's single probe or the operator): children then start
    their measured work immediately.  Evaluated at child-launch time —
    the parent latches the verdict AFTER this module loads.
    """
    if hatch_flag("POSEIDON_BENCH_NO_PROBE"):
        return 0
    return int(hatch_float("POSEIDON_DEVICE_LOCK_TIMEOUT")) + 300


def _probe_matmul() -> bool:
    """One end-to-end backend check in a disposable subprocess.

    A matmul, not jax.devices(): eager ops COMPILE, so this verifies the
    whole chain — tunnel, device, and the remote-compile service.  The
    observed mid-ladder failure mode (2026-07-31) was a live tunnel
    whose compile service died: device listing succeeds, every child
    then crashes on its first fresh compile.  No compile cache is
    enabled in the probe, so a cached executable can't mask a dead
    service."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax,jax.numpy as jnp;"
             "print(float((jnp.ones((64,64))@jnp.ones((64,64))).sum()))"],
            capture_output=True, text=True, timeout=300,
        )
        # ones(64,64) @ ones(64,64) sums to 64**3 = 262144.
        return probe.returncode == 0 and "262144" in probe.stdout
    except subprocess.TimeoutExpired:
        return False


def _latch_cpu_env() -> None:
    """Rewrite this process's environment to the clean-CPU one (children
    inherit it) and release the device flock — the bench will not touch
    the chip again."""
    from poseidon_tpu.utils.envutil import clean_cpu_env, release_device_lock

    env = clean_cpu_env(os.path.dirname(os.path.abspath(__file__)))
    env["POSEIDON_BENCH_NO_PROBE"] = "1"
    os.environ.clear()
    os.environ.update(env)
    release_device_lock()


def _stage_failed_recheck(res: dict) -> bool:
    """After a FAILED stage in accelerator mode, re-verify the backend.

    The tunnel's compile service has died mid-ladder in both live
    sessions (period ~30 min); with the verdict latched at start, every
    remaining stage then burned its timeout against a backend that
    could no longer compile, losing stages a CPU fallback would have
    completed.  Returns True when the backend is gone and the caller
    should retry the stage once on the freshly latched CPU environment;
    a healthy re-probe (or already-CPU mode) returns False — the
    failure was the stage's own.
    """
    if res.get("ok"):
        return False
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return False
    if _probe_matmul():
        return False
    print("# backend died mid-ladder (re-probe failed); latching CPU "
          "and retrying the failed stage", file=sys.stderr)
    _latch_cpu_env()
    return True


def _parent_probe_and_latch() -> None:
    """Probe the accelerator ONCE, in the parent; latch the verdict for
    every child.

    The TPU tunnel can wedge (worker crash leaves every op hanging
    forever).  A subprocess probe detects that without hanging this
    process.  Verdicts:

    - live: children run on the accelerator with no further probing; the
      PARENT holds the host-wide device flock for the whole bench (the
      children run sequentially under it, which is the serialization the
      lock exists for — concurrent backend init is a wedge trigger);
    - dead/busy: the parent's own environment is rewritten to the clean
      CPU one, so every child inherits `backend: "cpu"` without spending
      a single additional probe second on the dead tunnel.
    """
    if hatch_flag("POSEIDON_BENCH_NO_PROBE"):
        return  # operator already latched a verdict (CPU dry-run mode)
    from poseidon_tpu.utils.envutil import (
        clean_cpu_env,
        serialize_device_access,
    )

    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # Explicit CPU request: the env var alone is NOT enough when an
        # accelerator-plugin site hook is present (it re-pins the
        # platform and its client init hangs on a dead tunnel even for
        # CPU-pinned children) — latch the CLEAN cpu env, probe nothing.
        env = clean_cpu_env(os.path.dirname(os.path.abspath(__file__)))
        env["POSEIDON_BENCH_NO_PROBE"] = "1"
        os.environ.clear()
        os.environ.update(env)
        return

    locked = serialize_device_access()  # $POSEIDON_DEVICE_LOCK_TIMEOUT
    if locked:
        ok = _probe_matmul()
    else:
        # Another process owns the chip and is not yielding: CPU fallback
        # beats racing it (the race wedges the tunnel for both).
        print("# device lock busy; not contending for the accelerator",
              file=sys.stderr)
        ok = False
    if ok:
        os.environ["POSEIDON_BENCH_NO_PROBE"] = "1"
        print("# accelerator probe ok; children skip probing",
              file=sys.stderr)
        return
    print("# accelerator unreachable; latching CPU for all children",
          file=sys.stderr)
    # The latch also releases the flock: this process will never touch
    # the chip again, and holding the exclusive lock through an
    # hours-long CPU ladder would block any recovered tunnel's real
    # users (service, tools) behind a bench that no longer wants the
    # hardware.
    _latch_cpu_env()


def _ensure_live_backend() -> None:
    """Child-side backend guard.

    Under the parent driver this is a no-op: the parent probed once and
    latched the verdict into the environment.  Only a MANUALLY invoked
    child (``bench.py --child rung ...`` for triage) still probes here,
    re-exec'ing itself onto the clean CPU environment when the
    accelerator is dead — same semantics the parent applies, in process-
    replacement form because jax may already be importable.
    """
    if hatch_flag("POSEIDON_BENCH_NO_PROBE"):
        return
    before = dict(os.environ)
    _parent_probe_and_latch()

    def _sans_latch(env):
        return {k: v for k, v in env.items()
                if k != "POSEIDON_BENCH_NO_PROBE"}

    if _sans_latch(dict(os.environ)) != _sans_latch(before):
        # The latch rewrote the environment (CPU pin, plugin strip,
        # PYTHONPATH rewrite — any of them): restart on it.  Env edits
        # cannot undo the plugin's already-installed import hooks in
        # THIS interpreter, whose first jax op would still hang on a
        # dead tunnel.  The live-verdict path sets only the latch flag
        # and keeps running here (an execve would drop the held device
        # flock: the fd is close-on-exec).
        os.execve(sys.executable, [sys.executable] + sys.argv,
                  dict(os.environ))


def _task_population(num_tasks: int, num_ecs: int, seed: int):
    """num_ecs distinct task shapes, uniform multiplicity, seed-fresh."""
    rng = np.random.default_rng(seed)
    ec_cpu = rng.integers(100, 4000, size=num_ecs)
    ec_ram = rng.integers(1 << 18, 1 << 22, size=num_ecs)
    ec_of_task = rng.integers(0, num_ecs, size=num_tasks)
    return ec_cpu, ec_ram, ec_of_task


def build_cluster(num_machines: int, num_tasks: int, num_ecs: int, seed=0):
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    state = ClusterState()
    # Machine fleet: 3 hardware shapes (the trace-like heterogeneity).
    shapes = [(16000, 64 << 20), (32000, 128 << 20), (64000, 256 << 20)]
    for i in range(num_machines):
        cpu, ram = shapes[i % len(shapes)]
        state.node_added(
            MachineInfo(
                uuid=generate_uuid(f"bench-m{i}"),
                cpu_capacity=cpu,
                ram_capacity=ram,
                task_slots=64,
            )
        )
    submit_population(state, num_tasks, num_ecs, seed)
    return state


def submit_population(state, num_tasks: int, num_ecs: int, seed: int):
    from poseidon_tpu.graph.state import TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    ec_cpu, ec_ram, ec_of_task = _task_population(num_tasks, num_ecs, seed)
    for i in range(num_tasks):
        e = int(ec_of_task[i])
        state.task_submitted(
            TaskInfo(
                uid=task_uid(f"bench-job-s{seed}", i),
                job_id=f"bench-job-{e}",
                cpu_request=int(ec_cpu[e]),
                ram_request=int(ec_ram[e]),
            )
        )


def contended_cluster(machines: int = 40, ecs: int = 24, per_ec: int = 10,
                      prefix: str = "cc"):
    """A small cluster whose demand sits just past comfortable capacity,
    so the greedy start cannot host-certify and the device ladder runs
    real iterations — the shared recipe the smoke gates (trace-smoke's
    counter-track window, profile-smoke) and the telemetry tests use to
    guarantee a convergence curve gets captured.  ONE definition so a
    threshold retune cannot leave one gate quietly un-contended."""
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    state = ClusterState()
    for i in range(machines):
        state.node_added(MachineInfo(
            uuid=generate_uuid(f"{prefix}-m{i}"), cpu_capacity=4000,
            ram_capacity=1 << 24, task_slots=8,
        ))
    for e in range(ecs):
        for i in range(per_ec):
            state.task_submitted(TaskInfo(
                uid=task_uid(f"{prefix}-{e}", i), job_id=f"{prefix}-{e}",
                cpu_request=300 + 37 * e, ram_request=1 << 18,
            ))
    return state


def churn_step(state, rng, frac: int = 100):
    """Replace 1/frac of the tasks with same-shape resubmissions — the
    steady-state churn step, shared by the measured churn loop and the
    restart-recovery measurement so both see identical semantics."""
    from poseidon_tpu.graph.state import TaskInfo

    uids = list(state.tasks.keys())
    pick = rng.choice(len(uids), size=max(1, len(uids) // frac),
                      replace=False)
    for k in pick:
        uid = uids[k]
        t = state.tasks.get(uid)
        if t is None:
            continue
        state.task_removed(uid)
        state.task_submitted(
            TaskInfo(uid=uid, job_id=t.job_id,
                     cpu_request=t.cpu_request,
                     ram_request=t.ram_request)
        )


def run_rung(machines: int, tasks: int, ecs: int, rounds: int,
             verbose: bool) -> dict:
    """One ladder rung: cold round, fresh-population waves, churn rounds."""
    import jax

    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner

    backend = jax.devices()[0].platform
    # cold_s honesty: report whether this child started with a non-empty
    # persistent compile cache (cold_s is then cache-load, not compile).
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", "")
    cache_warm = False
    if cache_dir and os.path.isdir(cache_dir):
        with os.scandir(cache_dir) as entries:
            cache_warm = any(True for _ in entries)
    state = build_cluster(machines, tasks, ecs, seed=0)
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))

    # Partial-progress lines: each completed stage prints a JSON line
    # (ok=False + "partial" until the rung finishes), so a parent-side
    # timeout mid-rung still salvages every number measured so far —
    # on a slow/unproven backend the partial cold/wave figures are the
    # artifact that matters.
    partial = {
        "machines": machines, "tasks": tasks, "backend": backend,
        "cache_warm": cache_warm, "ok": False,
    }

    t0 = time.perf_counter()
    _, metrics = planner.schedule_round()
    cold_s = time.perf_counter() - t0
    converged = metrics.converged
    partial.update(cold_s=round(cold_s, 4), partial="after cold round")
    print(json.dumps(partial), flush=True)
    if verbose:
        print(f"# [{machines}] cold: {cold_s:.3f}s placed={metrics.placed} "
              f"unsched={metrics.unscheduled}", file=sys.stderr)

    # Compile the remaining shape ladder before the measured loops, as a
    # production server does at startup (FirmamentTPUConfig.precompile):
    # cold_s above keeps the honest compile-included number; the wave and
    # churn percentiles then measure steady state, not one-off compiles.
    t0 = time.perf_counter()
    shapes = planner.precompile(max_ecs=256)
    precompile_s = time.perf_counter() - t0
    if verbose:
        print(f"# [{machines}] precompile: {shapes} shapes "
              f"{precompile_s:.1f}s", file=sys.stderr)

    # Full waves, each a FRESH population: drain everything, submit new
    # random shapes (new seed => new ECs/costs; nothing bit-identical).
    # Per-round DEVICE series ride the artifact alongside the wall-time
    # percentiles (solve iterations, BF sweeps, dispatches, ladder entry
    # phase) so tools/bench_compare.py gates device work directly — a
    # regression that trades iterations for overlapped wall time (or
    # vice versa) is visible either way.
    wave_lat = []
    wave_solve_iters = []
    wave_bf_sweeps = []
    wave_device_calls = []
    wave_entry_phase = []
    wave_telem_samples = []
    wave_telem_iters_to_90 = []
    wave_sharded_bands = []
    wave_shard_imbalance = []
    # Solver-tier fingerprint of the rung (sorted unique): bench_compare
    # refuses to diff device-work series across DIFFERENT tier mixes —
    # a sharded rung's per-device counts are not a single-chip rung's.
    solve_tiers = set()
    placed = unsched = 0
    objective = 0
    for r in range(rounds):
        for uid in list(state.tasks.keys()):
            state.task_removed(uid)
        submit_population(state, tasks, ecs, seed=r + 1)
        t0 = time.perf_counter()
        _, metrics = planner.schedule_round()
        dt = time.perf_counter() - t0
        wave_lat.append(dt)
        wave_solve_iters.append(metrics.iterations)
        wave_bf_sweeps.append(metrics.bf_sweeps)
        wave_device_calls.append(metrics.device_calls)
        wave_entry_phase.append(metrics.ladder_entry_phase)
        wave_telem_samples.append(metrics.telem_samples)
        wave_telem_iters_to_90.append(metrics.telem_iters_to_90)
        wave_sharded_bands.append(metrics.sharded_bands)
        wave_shard_imbalance.append(metrics.shard_imbalance)
        solve_tiers.add(metrics.solve_tier)
        placed, unsched = metrics.placed, metrics.unscheduled
        objective = metrics.objective
        converged = converged and metrics.converged
        if verbose:
            print(f"# [{machines}] wave {r}: {dt:.3f}s "
                  f"solve={metrics.solve_seconds:.3f}s placed={placed} "
                  f"unsched={unsched} gap={metrics.gap_bound} "
                  f"iters={metrics.iterations} bf={metrics.bf_sweeps} "
                  f"calls={metrics.device_calls} "
                  f"entry={metrics.ladder_entry_phase} "
                  f"phases={metrics.solve_phase_iters} "
                  f"pruned={metrics.pruned_bands}/"
                  f"w{metrics.pruned_width}/"
                  f"esc{metrics.pruned_escalations} "
                  f"fresh={metrics.fresh_compiles}",
                  file=sys.stderr)
        partial.update(
            precompile_s=round(precompile_s, 4),
            wave_p50_s=round(float(np.percentile(wave_lat, 50)), 4),
            partial=f"after wave {r + 1}/{rounds}",
        )
        print(json.dumps(partial), flush=True)

    # Steady-state churn: replace 1% of tasks per round.  Same-shape
    # resubmissions keep EC ids stable, so these are the rounds the
    # delta-maintained cost planes (costmodel/delta.py) must serve —
    # the per-round hit/rebuild telemetry rides the artifact so a
    # silently-vanished incremental path is visible, not inferred.
    rng = np.random.default_rng(12345)
    churn_lat = []
    churn_delta_hits = []
    churn_solve_iters = []
    churn_device_calls = []
    churn_rows_rebuilt = churn_cols_rebuilt = 0
    for r in range(rounds):
        churn_step(state, rng)
        t0 = time.perf_counter()
        _, metrics = planner.schedule_round()
        dt = time.perf_counter() - t0
        churn_lat.append(dt)
        churn_delta_hits.append(metrics.cost_delta_hits)
        churn_solve_iters.append(metrics.iterations)
        churn_device_calls.append(metrics.device_calls)
        churn_rows_rebuilt += metrics.cost_rows_rebuilt
        churn_cols_rebuilt += metrics.cost_cols_rebuilt
        solve_tiers.add(metrics.solve_tier)
        converged = converged and metrics.converged
        if verbose:
            print(f"# [{machines}] churn {r}: {dt:.3f}s "
                  f"solve={metrics.solve_seconds:.3f}s "
                  f"iters={metrics.iterations} bf={metrics.bf_sweeps} "
                  f"calls={metrics.device_calls} "
                  f"delta_hits={metrics.cost_delta_hits} "
                  f"rows/cols_rebuilt={metrics.cost_rows_rebuilt}/"
                  f"{metrics.cost_cols_rebuilt}",
                  file=sys.stderr)

    # Recovery-to-first-placement: checkpoint the live state (placements
    # + solver warm frames), restore into a FRESH planner, apply one
    # churn step (a restart never lands on a perfectly quiet cluster),
    # and time the first round.  Within-process, so XLA compile cache is
    # warm — which matches a restarted service with the persistent
    # on-disk cache (envutil.enable_compilation_cache).
    import tempfile

    from poseidon_tpu.graph.snapshot import load_checkpoint, save_checkpoint

    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "bench.ckpt")
        save_checkpoint(state, planner, ckpt)
        state_r, planner_r = load_checkpoint(ckpt)
        churn_step(state_r, rng)
        t0 = time.perf_counter()
        _, m_restart = planner_r.schedule_round()
        restart_s = time.perf_counter() - t0

    return {
        "machines": machines,
        "tasks": tasks,
        "backend": backend,
        "cache_warm": cache_warm,
        "cold_s": round(cold_s, 4),
        "precompile_s": round(precompile_s, 4),
        "wave_p50_s": round(float(np.percentile(wave_lat, 50)), 4),
        "churn_p50_s": round(float(np.percentile(churn_lat, 50)), 4),
        # Per-round device-work series (bench_compare gates these as
        # counts, machine-independent — wall time alone can hide a
        # device-work regression behind host overlap and vice versa).
        "wave_solve_iters": wave_solve_iters,
        "wave_bf_sweeps": wave_bf_sweeps,
        "wave_device_calls": wave_device_calls,
        "wave_entry_phase": wave_entry_phase,
        # Convergence-telemetry roll-ups (informational, not gated:
        # half-life / drain shift with tie-breaks; the curve itself
        # lives in the round history + Perfetto counter tracks).
        "wave_telem_samples": wave_telem_samples,
        "wave_telem_iters_to_90": wave_telem_iters_to_90,
        "wave_sharded_bands": wave_sharded_bands,
        "wave_shard_imbalance": wave_shard_imbalance,
        "solve_tiers": sorted(solve_tiers),
        "churn_solve_iters": churn_solve_iters,
        "churn_device_calls": churn_device_calls,
        "churn_delta_hits": churn_delta_hits,
        "churn_rows_rebuilt": churn_rows_rebuilt,
        "churn_cols_rebuilt": churn_cols_rebuilt,
        "restart_round_s": round(restart_s, 4),
        "restart_iters": m_restart.iterations,
        "placed": placed,
        "unscheduled": unsched,
        "objective": objective,
        "converged": converged,
        "ok": True,
    }


def run_trace(machines: int, tasks: int, rounds: int) -> dict:
    """BASELINE config 5: Google-trace-shaped replay with incremental
    delta re-solve (poseidon_tpu.replay) — churning jobs/completions
    between rounds rather than synthetic drain/resubmit.

    Two stages: the steady-state replay at full scale, then a PRESSURE
    replay (smaller fleet, 10% of machines removed mid-trace, continuous
    rebalancing) that forces the PREEMPT/MIGRATE delta paths — the two
    delta types a pure submit/complete replay never emits (round-3
    review: ``preempted: 0, migrated: 0`` left them untested at scale).
    """
    import jax

    from poseidon_tpu.replay.driver import ReplayDriver
    from poseidon_tpu.replay.trace import synthesize_trace

    # Per-round stderr breadcrumbs: the round-5 TPU trace child spent
    # its entire 3000 s budget with no observable output.
    os.environ.setdefault("POSEIDON_REPLAY_PROGRESS", "1")
    events = synthesize_trace(
        machines, max(tasks // 8, 1), horizon_s=rounds * 10.0, seed=3
    )
    driver = ReplayDriver(events, round_interval_s=10.0)
    report = driver.run(max_rounds=rounds)
    out = report.summary()
    # Partial artifact before the pressure stage: a timeout there must
    # not discard the completed steady-state replay.
    out["backend"] = jax.devices()[0].platform
    out["ok"] = True
    out["pressure"] = {"ok": False, "error": "not run"}
    print(json.dumps(out), flush=True)

    p_machines = min(max(machines // 4, 200), 2500)
    p_rounds = min(rounds, 20)
    p_events = synthesize_trace(
        p_machines, max(p_machines * 10 // 8, 1),
        horizon_s=p_rounds * 10.0, seed=4, remove_frac=0.10,
    )
    p_driver = ReplayDriver(
        p_events, round_interval_s=10.0, reschedule_running=True,
    )
    p_summary = p_driver.run(max_rounds=p_rounds).summary()
    p_summary["ok"] = True
    out["pressure"] = p_summary
    return out


def run_features(machines: int, rounds: int) -> dict:
    """BASELINE configs 2-4 at cluster scale: node selectors (2),
    pod-level affinity with multi-round scheduling (3), gang
    scheduling (4).  Each sub-report carries both the latency AND the
    semantic predicate (violations must be zero) — a fast round that
    breaks affinity/atomicity would be worthless.
    """
    import jax

    from poseidon_tpu.check.ledger import (
        CompileLedger,
        NumericsLedger,
        TransferLedger,
    )
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.costmodel.selectors import IN_SET
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.obs import trace as obs_trace
    from poseidon_tpu.utils import stagetimer
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    # Per-stage sub-timings for the constraint rounds.  PR 2 made the
    # affinity config mask-cheap (mask build ~0.3 s of a 2.25 s round)
    # and showed the gang config was SOLVE-side-bound (15.2 s of a
    # 17.1 s round in band solves; mask build 0.001 s) — round 7 then
    # profiled that solve time down to compile storms + uncertifiable
    # warm starts and fixed both (pruned planes, greedy retry passes,
    # repair-start host certificates).  The artifact carries where the
    # round actually went (mask build vs cost build vs solve) next to
    # the headline latency so the next shift in the bottleneck is
    # visible, not inferred.
    os.environ["POSEIDON_STAGE_TIMERS"] = "1"

    def _stage_timings() -> dict:
        snap = stagetimer.snapshot()
        timings = {}
        for label, key in (
            ("mask_build_s", "round.mask_build"),
            ("cost_build_s", "round.cost_build"),
            ("solve_s", "round.solve_band"),
            ("view_build_s", "round.view_build"),
        ):
            total, _calls = snap.get(key, (0.0, 0))
            timings[label] = round(total, 4)
        return timings

    out = {"backend": jax.devices()[0].platform, "ok": False}
    tasks = machines * 5

    # --- config 2: node selectors (half the fleet labeled; selector
    # tasks must land only there, plain tasks anywhere).
    state = ClusterState()
    for i in range(machines):
        state.node_added(MachineInfo(
            uuid=generate_uuid(f"feat-m{i}"), cpu_capacity=32000,
            ram_capacity=128 << 20, task_slots=64,
            labels={"zone": "z1" if i % 2 == 0 else "z2"},
        ))
    zoned = {}
    for i in range(tasks):
        sel = ((IN_SET, "zone", ("z1",)),) if i % 4 == 0 else ()
        uid = task_uid("feat-sel", i)
        zoned[uid] = bool(sel)
        state.task_submitted(TaskInfo(
            uid=uid, job_id=f"fj{i % 16}", cpu_request=200,
            ram_request=1 << 19, selectors=sel,
        ))
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    lat = []
    fresh_per_round = []
    delta_hits_per_round = []
    m = None
    for r in range(rounds):
        t0 = time.perf_counter()
        if r == 0:
            # Cold round: compiles are expected and paid here.
            _, m = planner.schedule_round()
        else:
            # Warm churn rounds ride the compile ledger at budget 0:
            # PR 3's hard-won invariant ("zero fresh compiles in a warm
            # round") enforced in-band — a retrace regression fails the
            # bench with the compiled program names, instead of hiding
            # in round_p50_s the way the 15.2 s gang round did.
            # The numerics window rides next to the compile/transfer
            # ones: validating every host_fetch leaf (finite floats,
            # int32 fetch headroom) at budget 0, so a wrapped or
            # saturated solver value fails the bench naming the
            # offending array instead of corrupting placements.
            with CompileLedger(budget=0, label=f"warm selector round {r}"), \
                    TransferLedger(
                        budget=0, label=f"warm selector round {r}"), \
                    NumericsLedger(
                        budget=0, label=f"warm selector round {r}"):
                _, m = planner.schedule_round()
        lat.append(time.perf_counter() - t0)
        fresh_per_round.append(m.fresh_compiles)
        delta_hits_per_round.append(m.cost_delta_hits)
        submit_population(state, tasks // 100, 16, seed=r + 1)  # churn
    violations = zoned_placed = 0
    for uid, is_zoned in zoned.items():
        if not is_zoned:
            continue
        t = state.tasks.get(uid)
        if t is None or t.scheduled_to is None:
            continue
        zoned_placed += 1
        if state.machines[t.scheduled_to].labels.get("zone") != "z1":
            violations += 1
    n_zoned = sum(zoned.values())
    out["selectors"] = {
        "round_p50_s": (
            round(float(np.percentile(lat, 50)), 4) if lat else 0.0
        ),
        "violations": violations,
        # Positive predicate too: zero violations with zero placements
        # would be a vacuous pass (capacity holds them all, so all must
        # place).
        "zoned_placed": zoned_placed,
        "zoned_total": n_zoned,
        # Fresh XLA compiles per round (check/ledger.py): round 0 pays
        # the cold compiles; every later (warm churn) round must report
        # 0 — PR 3's invariant, now a visible artifact column.
        "fresh_compiles": fresh_per_round,
        "warm_fresh_compiles": sum(fresh_per_round[1:]),
        # Delta-plane serves per round (all-new churn ECs legitimately
        # rebuild full: the incremental path's home is the same-shape
        # churn loop in run_rung, whose artifact carries its own
        # churn_delta_hits series).
        "cost_delta_hits": delta_hits_per_round,
    }
    # Partial line per completed stage (the parent salvages these on a
    # timeout, same contract as the rung/trace children).
    print(json.dumps(out), flush=True)

    # --- config 3: pod-level affinity, multi-round (follower tasks
    # co-locate with a running "db" target placed in an earlier round).
    state = ClusterState()
    for i in range(machines):
        state.node_added(MachineInfo(
            uuid=generate_uuid(f"aff-m{i}"), cpu_capacity=32000,
            ram_capacity=128 << 20, task_slots=64,
        ))
    n_targets = machines // 10
    for i in range(n_targets):
        # Anti-affinity to their shared role spreads targets one per
        # machine (without it, 100 identical-cost targets pack onto ~2
        # machines whose task slots then can't hold any follower —
        # measured at 1000 machines: 28/100 co-located, all failures
        # slot-capacity, not affinity).
        state.task_submitted(TaskInfo(
            uid=task_uid("aff-db", i), job_id="aff-db",
            cpu_request=500, ram_request=1 << 19,
            labels={"app": f"db{i}", "role": "db"},
            pod_anti_affinity=((IN_SET, "role", ("db",)),),
        ))
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    planner.schedule_round()  # targets land and RUN
    for i in range(n_targets):
        state.task_submitted(TaskInfo(
            uid=task_uid("aff-web", i), job_id="aff-web",
            cpu_request=200, ram_request=1 << 19,
            pod_affinity=((IN_SET, "app", (f"db{i}",)),),
        ))
    stagetimer.reset()
    t0 = time.perf_counter()
    planner.schedule_round()
    aff_s = time.perf_counter() - t0
    colocated = sum(
        1 for i in range(n_targets)
        if state.tasks[task_uid("aff-web", i)].scheduled_to is not None
        and state.tasks[task_uid("aff-web", i)].scheduled_to
        == state.tasks[task_uid("aff-db", i)].scheduled_to
    )
    ma = planner.last_metrics
    out["pod_affinity"] = {
        "round_s": round(aff_s, 4),
        "targets": n_targets,
        "colocated": colocated,
        "fresh_compiles": ma.fresh_compiles,
        # Full round metrics in the one schema-versioned wire format
        # (RoundMetrics.to_dict — same dict the flight recorder and the
        # Prometheus exporter consume).
        "round_metrics": ma.to_dict(),
        **_stage_timings(),
    }
    print(json.dumps(out), flush=True)

    # --- config 4: gang scheduling (feasible gangs place whole;
    # an oversized gang places nothing — atomicity at scale).
    state = ClusterState()
    for i in range(machines):
        state.node_added(MachineInfo(
            uuid=generate_uuid(f"gang-m{i}"), cpu_capacity=32000,
            ram_capacity=128 << 20, task_slots=8,
        ))
    gang_size = 32
    n_gangs = machines // 20
    for g in range(n_gangs):
        for i in range(gang_size):
            state.task_submitted(TaskInfo(
                uid=task_uid(f"gang{g}", i), job_id=f"gang-{g}",
                cpu_request=1000, ram_request=1 << 20, gang=True,
            ))
    # One gang that can never fit (more members than total free slots
    # after the others): atomicity demands zero of it places.
    big = machines * 8 + 1
    for i in range(big):
        state.task_submitted(TaskInfo(
            uid=task_uid("gang-big", i), job_id="gang-big",
            cpu_request=100, ram_request=1 << 18, gang=True,
        ))
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    stagetimer.reset()
    t0 = time.perf_counter()
    # The gang round's compile keys are all warm by now (configs 2-3
    # solved the same padded buckets this process) and its solves are
    # host-certified at every measured scale (PR 3: zero dispatches at
    # 10k) — so a fresh compile here IS the silent-retrace bug class,
    # asserted at budget 0 exactly like the warm rounds.
    with CompileLedger(budget=0, label="gang round"), \
            TransferLedger(budget=0, label="gang round"), \
            NumericsLedger(budget=0, label="gang round"):
        _, mg = planner.schedule_round()
    gang_s = time.perf_counter() - t0
    partial_gangs = placed_gangs = 0
    for g in range(n_gangs):
        placed_n = sum(
            1 for i in range(gang_size)
            if state.tasks[task_uid(f"gang{g}", i)].scheduled_to
        )
        if placed_n == gang_size:
            placed_gangs += 1
        elif placed_n > 0:
            partial_gangs += 1
    big_placed = sum(
        1 for i in range(big)
        if state.tasks[task_uid("gang-big", i)].scheduled_to
    )
    out["gang"] = {
        "round_s": round(gang_s, 4),
        "gangs": n_gangs,
        "placed_gangs": placed_gangs,
        "partial_gangs": partial_gangs,
        "oversized_gang_placed": big_placed,
        # Solve-side telemetry: the gang round's latency lives in the
        # solves (repair re-solves included — their work folds into
        # solve_iters/bf_sweeps via the planner's hidden counters).
        "solve_iters": mg.iterations,
        "bf_sweeps": mg.bf_sweeps,
        "device_calls": mg.device_calls,
        "fresh_compiles": mg.fresh_compiles,
        "repair_firings": mg.repair_firings,
        "pruned": {
            "bands": mg.pruned_bands,
            "shortlist_width": mg.pruned_width,
            "price_out_rounds": mg.pruned_price_out_rounds,
            "escalations": mg.pruned_escalations,
        },
        "round_metrics": mg.to_dict(),
        **_stage_timings(),
    }
    # With POSEIDON_TRACE=1 the whole features run recorded spans
    # (round -> mask/cost/solve/view stage nesting): export the
    # Perfetto-loadable artifact next to the numbers.
    if obs_trace.tracing_enabled():
        trace_path = os.path.join("out", "trace_features.json")
        obs_trace.export_chrome_trace(trace_path)
        out["trace_artifact"] = trace_path
    out["ok"] = (
        violations == 0
        and zoned_placed == n_zoned        # selectors place AND respect
        and colocated == n_targets
        and placed_gangs == n_gangs        # feasible gangs place WHOLE
        and partial_gangs == 0
        and big_placed == 0
    )
    return out


def run_soak(machines: int, rounds: int, plan: str, seed: int) -> dict:
    """Soak mode: N rounds of the FULL glue+service stack under a named
    fault plan (poseidon_tpu/chaos) at small scale, gating the
    robustness claims — convergence, zero fake-kube/scheduler state
    divergence after every round, zero fresh compiles on warm rounds,
    and seed-reproducible placements.  A failure writes a flight-
    recorder trace under out/soak/ that replay.redrive_flight re-drives
    offline.  ``make soak-smoke`` runs this via tests/test_soak_smoke.py."""
    from poseidon_tpu.chaos import run_soak as chaos_run_soak

    out = chaos_run_soak(
        machines=machines, rounds=rounds, plan=plan, seed=seed
    )
    # The determinism gate: a second run with the same seed must place
    # identically (per-round placement digests compare equal).
    if out.get("ok"):
        rerun = chaos_run_soak(
            machines=machines, rounds=rounds, plan=plan, seed=seed
        )
        out["deterministic"] = rerun.get("digests") == out.get("digests")
        out["ok"] = bool(out["ok"] and rerun.get("ok")
                         and out["deterministic"])
    return out


def _throughput_session(machines: int, seed: int, streaming: bool, *,
                        seconds: float = 0.0, fixed_rounds: int = 0,
                        pods_per_round: int = 24) -> dict:
    """One full-stack continuous-churn session (no faults): FakeKube +
    watchers + glue loop + Firmament service, driven either for a fixed
    DURATION (``seconds`` — the throughput leg: churn and round as fast
    as the engine completes them) or for a fixed ROUND COUNT
    (``fixed_rounds`` — the byte-identity leg: every round drained
    before the next so streaming and synchronous runs see identical
    admitted sets and must place identically).

    Flips POSEIDON_STREAMING for the session and restores it — callers
    run back-to-back streaming/synchronous legs in one child process."""
    import numpy as np

    from poseidon_tpu.chaos.soak import (
        _NODE_CPU,
        _NODE_RAM,
        _POD_SHAPES,
        _await,
        _digest,
        _placement_views,
    )
    from poseidon_tpu.check.ledger import fresh_compile_count
    from poseidon_tpu.glue.fake_kube import FakeKube, Node, Pod
    from poseidon_tpu.glue.poseidon import Poseidon
    from poseidon_tpu.ops.transport import bucket_size
    from poseidon_tpu.service.server import FirmamentTPUServer
    from poseidon_tpu.utils.config import FirmamentTPUConfig, PoseidonConfig

    # Save/restore of the raw env slot, not a semantic read — the
    # engine itself reads the flag through the hatch registry.
    prev = os.environ.get("POSEIDON_STREAMING")  # posecheck: ignore[hatch-registry]
    os.environ["POSEIDON_STREAMING"] = "1" if streaming else "0"
    server = poseidon = None
    try:
        server = FirmamentTPUServer(
            address="127.0.0.1:0",
            config=FirmamentTPUConfig(
                precompile=True,
                max_ecs=bucket_size(len(_POD_SHAPES) * 4, lo=8),
                max_machines=0,
            ),
        ).start()
        kube = FakeKube()
        cfg = PoseidonConfig(
            firmament_address=server.address,
            scheduling_interval=3600,
            crash_loop_budget=4,
            crash_backoff_s=0.01,
            crash_backoff_max_s=0.05,
        )
        poseidon = Poseidon(
            kube, config=cfg, run_loop=False
        ).start(health_timeout=30)
        for i in range(machines):
            kube.add_node(Node(
                name=f"m{i:04d}",
                cpu_capacity=_NODE_CPU, ram_capacity=_NODE_RAM,
            ))
        synced = _await(
            lambda: all(
                poseidon.shared.get_node(f"m{i:04d}") is not None
                for i in range(machines)
            ),
            30.0,
        )
        if not (synced and poseidon.drain_watchers(timeout=30.0)):
            return {"ok": False, "error": "node sync never drained"}
        server.servicer.ensure_precompiled()

        rng = np.random.default_rng(seed)
        counter = 0

        def churn() -> list:
            """This round's workload: create a cohort, complete the
            oldest Running half-cohort (bounded live population)."""
            nonlocal counter
            created = []
            for _ in range(pods_per_round):
                cpu, ram = _POD_SHAPES[int(rng.integers(len(_POD_SHAPES)))]
                name = f"tp-{counter:06d}"
                counter += 1
                kube.create_pod(Pod(
                    name=name, cpu_request=cpu, ram_request=ram,
                    owner_uid=f"tpjob-{counter % 7}",
                ))
                created.append(f"default/{name}")
            # Snapshot copy (list_pods) — the streaming enact worker
            # mutates the live registry concurrently in the duration leg.
            running = sorted(
                p.key for p in kube.list_pods() if p.phase == "Running"
            )
            for key in running[:pods_per_round // 2]:
                kube.set_pod_phase(key, "Succeeded")
            return created

        rounds = 0
        staleness: list = []
        overlaps: list = []
        deferred = 0
        digests: list = []
        warm_fresh = 0
        fresh_mark = None
        t0 = time.perf_counter()
        deadline = t0 + seconds if seconds else None
        while True:
            if deadline is not None and time.perf_counter() >= deadline:
                break
            if fixed_rounds and rounds >= fixed_rounds:
                break
            created = churn()
            if fixed_rounds:
                # Identity leg only: barrier every delta into the view
                # before the cut, so both modes admit identical sets.
                _await(
                    lambda: all(
                        poseidon.shared.uid_for_pod(k) is not None
                        for k in created
                    ),
                    10.0,
                )
                poseidon.drain_watchers(timeout=10.0)
            delay = poseidon.try_round()
            if delay is None:
                return {"ok": False, "error": poseidon.fatal}
            rounds += 1
            m = server.servicer.planner.last_metrics
            if m is not None:
                staleness.append(float(m.admission_staleness_s))
                overlaps.append(float(m.overlap_fraction))
                deferred += int(m.admission_deferred)
            if fixed_rounds:
                if not poseidon.drain_rounds(timeout=30.0):
                    return {"ok": False, "error": "enact never drained"}
                poseidon.drain_watchers(timeout=10.0)
                kube_truth, sched_view = _placement_views(
                    kube, poseidon, server
                )
                if kube_truth != sched_view:
                    return {
                        "ok": False, "error": f"divergence at round {rounds}",
                    }
                digests.append(_digest(kube_truth))
            if rounds == 2:
                # Warm window opens after the engine has seen both the
                # wave and churn shapes once.
                fresh_mark = fresh_compile_count()
        wall = time.perf_counter() - t0
        if not poseidon.drain_rounds(timeout=60.0):
            return {"ok": False, "error": "final enactment never drained"}
        poseidon.drain_watchers(timeout=30.0)
        if fresh_mark is not None:
            warm_fresh = fresh_compile_count() - fresh_mark
        placed = poseidon.loop_stats.placed
        out = {
            "ok": True,
            "mode": "streaming" if streaming else "synchronous",
            "rounds": rounds,
            "placed": int(placed),
            "wall_s": round(wall, 3),
            "placements_per_sec": (
                round(placed / wall, 2) if wall > 0 else 0.0
            ),
            "overlap_fraction_mean": (
                round(float(np.mean(overlaps)), 4) if overlaps else 0.0
            ),
            "admission_staleness_p50_s": (
                round(float(np.percentile(staleness, 50)), 6)
                if staleness else 0.0
            ),
            "admission_staleness_p99_s": (
                round(float(np.percentile(staleness, 99)), 6)
                if staleness else 0.0
            ),
            "admission_deferred_total": int(deferred),
            "warm_fresh_compiles": int(warm_fresh),
            "digests": digests,
        }
        return out
    finally:
        if poseidon is not None:
            poseidon.stop()
        if server is not None:
            server.stop(grace=0.5)
        if prev is None:
            os.environ.pop("POSEIDON_STREAMING", None)
        else:
            os.environ["POSEIDON_STREAMING"] = prev


def run_throughput(machines: int, seconds: float, seed: int) -> dict:
    """Sustained-throughput rung (``--child throughput``): fixed-duration
    continuous churn through the FULL stack, streaming engine vs the
    round-synchronous loop on the same machine/workload generator —
    placements/sec, realized round-overlap fraction, and admission
    staleness p50/p99 — plus a fixed-round byte-identity leg (per-round
    drained, so both modes must produce identical placement digests).

    The result carries ``mode: "streaming"``; tools/bench_compare.py
    refuses to diff its series against a synchronous-mode artifact."""
    identity_sync = _throughput_session(
        machines, seed, streaming=False, fixed_rounds=6
    )
    identity_stream = _throughput_session(
        machines, seed, streaming=True, fixed_rounds=6
    )
    sync = _throughput_session(
        machines, seed, streaming=False, seconds=seconds
    )
    stream = _throughput_session(
        machines, seed, streaming=True, seconds=seconds
    )
    identity_ok = bool(
        identity_sync.get("ok") and identity_stream.get("ok")
        and identity_sync.get("digests") == identity_stream.get("digests")
    )
    out = {
        "ok": bool(sync.get("ok") and stream.get("ok") and identity_ok),
        "mode": "streaming",
        "machines": machines,
        "seconds": seconds,
        "identity_ok": identity_ok,
        "identity_rounds": len(identity_sync.get("digests") or []),
        "streaming": stream,
        "synchronous": sync,
        "placements_per_sec": stream.get("placements_per_sec", 0.0),
        "placements_per_sec_sync": sync.get("placements_per_sec", 0.0),
    }
    base = out["placements_per_sec_sync"]
    out["throughput_gain"] = (
        round(out["placements_per_sec"] / base, 3) if base else 0.0
    )
    if not identity_ok:
        out["error"] = (
            "streaming/synchronous placement digests diverged: "
            f"{identity_sync.get('digests')} vs "
            f"{identity_stream.get('digests')}"
        )
    return out


def run_scenario(machines: int, rounds: int, seed: int) -> dict:
    """Scenario rung (``--child scenario``): every named production-
    shaped scenario (poseidon_tpu/scenario) through the FULL glue+
    service stack, each one

    - driven in BOTH loop modes with all gates armed (byte-identity,
      budget-0 warm ledgers, tier vocabulary) and checked drain-
      equivalent (identical per-round placement AND delta digests), and
    - scored for robustness under chaos-seeded cost perturbation
      (objective-regression quantiles across POSEIDON_SCENARIO_SEEDS
      perturbed re-drives; scenario/score.py defines the metric).

    Like the throughput rung this is a BEHAVIOR claim, not a scale
    claim — it never pays ladder-sized machine counts.  The result
    carries ``mode: "streaming"`` (the identity legs drive both modes),
    so tools/bench_compare.py applies its mode guard."""
    from poseidon_tpu.obs.metrics import observe_scenario
    from poseidon_tpu.scenario import (
        SCENARIOS,
        drive_scenario,
        named_scenario,
        score_scenario,
    )

    scenarios = {}
    ok = True
    for name in SCENARIOS:
        plan = named_scenario(
            name, machines=machines, rounds=rounds, seed=seed
        )
        sync = drive_scenario(plan, streaming=False)
        stream = drive_scenario(plan, streaming=True)
        identity_ok = bool(
            sync.get("ok") and stream.get("ok")
            and sync.get("digests") == stream.get("digests")
            and sync.get("delta_digests") == stream.get("delta_digests")
        )
        score = score_scenario(plan, baseline=sync)
        entry = {
            "ok": bool(identity_ok and score.get("ok")),
            "identity_ok": identity_ok,
            "rounds": sync.get("rounds_run"),
            "scenario_digest": sync.get("scenario_digest"),
            "placements_per_sec": stream.get("placements_per_sec", 0.0),
            "placements_per_sec_sync": sync.get(
                "placements_per_sec", 0.0
            ),
            "robustness_score": score.get("robustness_score", 0.0),
            "regression_p90": score.get("regression_p90", 0.0),
            "placement_divergence": score.get(
                "placement_divergence", 0.0
            ),
            "admission_staleness_p50_s": sync.get(
                "admission_staleness_p50_s", 0.0
            ),
            "admission_staleness_p99_s": sync.get(
                "admission_staleness_p99_s", 0.0
            ),
            "objective": sync.get("objective", 0),
            "solve_tiers": sorted(set(sync.get("tiers") or [])),
        }
        if not identity_ok:
            entry["error"] = (
                "streaming/synchronous scenario drives diverged: "
                f"sync={sync.get('failure')} "
                f"stream={stream.get('failure')}"
            )
        elif not score.get("ok"):
            entry["error"] = f"perturbed gates: {score.get('failures')}"
        observe_scenario(
            name,
            robustness_score=entry["robustness_score"],
            placements_per_sec=entry["placements_per_sec"],
            regression_p90=entry["regression_p90"],
            placement_divergence=entry["placement_divergence"],
            admission_staleness_p50_s=entry["admission_staleness_p50_s"],
            admission_staleness_p99_s=entry["admission_staleness_p99_s"],
            ok=entry["ok"],
        )
        scenarios[name] = entry
        ok = ok and entry["ok"]
        # A stage line per scenario: a timed-out child still posts the
        # scenarios it finished (the parent salvages the last line).
        print(json.dumps({
            "ok": False, "partial": True, "mode": "streaming",
            "machines": machines, "rounds": rounds,
            "scenarios": dict(scenarios),
        }), flush=True)
    return {
        "ok": ok,
        "mode": "streaming",
        "machines": machines,
        "rounds": rounds,
        "scenarios": scenarios,
    }


def run_parity() -> dict:
    """BASELINE config 1 (100 nodes / 1k pods): TPU solver objective must
    equal the exact host oracle on the same transportation instance."""
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.ops.transport import solve_transport
    from poseidon_tpu.solver import oracle

    state = build_cluster(100, 1000, 50, seed=7)
    view = state.build_round_view()
    cm = get_cost_model("cpu_mem").build(view.ecs, view.machines)
    sol = solve_transport(
        cm.costs, view.ecs.supply, cm.capacity, cm.unsched_cost,
        arc_capacity=cm.arc_capacity,
    )
    expected = oracle.transport_objective(
        cm.costs, view.ecs.supply, cm.capacity, cm.unsched_cost,
        arc_capacity=cm.arc_capacity,
    )
    return {
        "parity_ok": bool(sol.objective == expected and sol.gap_bound == 0.0),
        "objective": int(sol.objective),
        "oracle_objective": int(expected),
        "ok": True,
    }


CLUSTER_RUNG = (100_000, 1_000_000)


def run_saturation_probe(E: int = 32, M: int = 16,
                         max_cost: int = 400) -> dict:
    """Drive aggregate supply to the int32 cliff and prove the
    numerics-discipline suite never wraps silently (the cluster rung's
    saturation leg; also run tiny by the bench smoke test).

    Two legs, covering both rails of the contract:

    - PAST the cliff: a supply vector whose int64 total leaves the
      certified int32 band must be REFUSED at dispatch by the
      host-boundary flow-sum certificate
      (``utils.numerics.certify_i32_total`` raising
      ``SaturationError``) — the in-kernel int32 flow reductions it
      covers would wrap.
    - AT the cliff: a dispatchable instance whose in-iteration active
      excess crosses 2^30 must come back with the telemetry ring's
      saturating lane CLAMPED AND FLAGGED (``_TR_SAT``), and the
      rail-riding fetched ring must be caught by the open
      ``NumericsLedger`` window.  The excess total stays positive
      everywhere — the silent two's-complement wrap this PR's telemetry
      fix removed is structurally unreachable.

    ``ok`` requires the certificate trip, the saturation flag, the
    ledger attribution, and no negative excess/flow anywhere."""
    from poseidon_tpu.check.ledger import NumericsLedger
    from poseidon_tpu.ops.transport import solve_transport
    from poseidon_tpu.utils.numerics import I32_MAX, SaturationError

    rng = np.random.default_rng(0)
    costs = rng.integers(0, max_cost, size=(E, M)).astype(np.int32)
    unsched = np.full(E, 5 * max_cost, dtype=np.int32)
    out: dict = {"E": E, "M": M, "ok": False}

    # Leg 1: past the cliff — dispatch must be refused, never solved.
    hot_supply = np.full(E, (1 << 31) // E, dtype=np.int32)
    capacity = np.full(M, 100_000_000 // M, dtype=np.int32)
    try:
        solve_transport(costs, hot_supply, capacity, unsched)
        out["certificate_tripped"] = False
    except SaturationError:
        out["certificate_tripped"] = True

    # Leg 2: at the cliff — solvable, saturating, flagged, attributed.
    supply = np.full(E, 2_000_000_000 // E, dtype=np.int32)
    with NumericsLedger(budget=None, label="saturation probe") as led:
        sol = solve_transport(costs, supply, capacity, unsched)
    t = sol.telemetry
    sat_samples = int(t.saturated_samples()) if t is not None else 0
    max_excess = int(t.active_excess.max()) if t is not None else 0
    min_excess = int(t.active_excess.min()) if t is not None else 0
    out.update(
        saturated_samples=sat_samples,
        ledger_anomalies=led.anomalies,
        max_active_excess=max_excess,
        excess_headroom_to_rail=I32_MAX - max_excess,
        wrap_observed=bool(min_excess < 0 or int(sol.flows.min()) < 0),
        ok=bool(
            out["certificate_tripped"]
            and sat_samples > 0
            and led.anomalies > 0
            and min_excess >= 0
            and int(sol.flows.min()) >= 0
        ),
    )
    return out


def run_cluster_rung(machines: int, tasks: int, ecs: int, rounds: int,
                     verbose: bool) -> dict:
    """The cluster-scale rung (default 100k machines / 1M tasks,
    ``CLUSTER_RUNG``): the sharded band tier serves the wave on the
    visible device mesh, with per-device work series in the artifact
    and a sharded-vs-dense objective-parity gate sampled at REDUCED
    scale — a full dense oracle solve at 100k is infeasible inside a
    bench budget, and the mesh kernel is bit-identical to the
    single-chip kernel at gate widths, so the reduced sample is the
    honest check (the randomized planner-level parity suite pins the
    same claim in tests).

    Partial-progress lines follow run_rung's protocol: each completed
    stage prints a superset JSON line, so a parent-side timeout
    mid-rung still salvages the parity verdict and any wave measured
    so far."""
    import jax

    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner

    backend = jax.devices()[0].platform
    n_dev = len(jax.devices())
    partial = {
        "machines": machines, "tasks": tasks, "backend": backend,
        "devices": n_dev, "ok": False,
    }
    if n_dev < 2:
        return {**partial,
                "error": "cluster rung needs a multi-device mesh "
                         "(real, or JAX_PLATFORMS=cpu + XLA_FLAGS="
                         "--xla_force_host_platform_device_count=N)"}
    # The sharded tier is opt-in (hatch default OFF); this rung IS the
    # opt-in.  A subprocess child, so the mutation is contained.
    os.environ["POSEIDON_SHARDED_BANDS"] = "1"

    def _parity_round(sharded: bool):
        # Same reduced instance both legs (build_cluster is seeded).
        # The gate thresholds are production-tuned for cluster widths;
        # the parity sample lowers them so the tier actually serves
        # the reduced wave instead of (rightly) declining it.
        os.environ["POSEIDON_SHARDED_BANDS"] = "1" if sharded else "0"
        os.environ["POSEIDON_SHARDED_MIN_COLS"] = "1024"
        os.environ["POSEIDON_SHARDED_MIN_CONTENTION"] = "1"
        try:
            st = build_cluster(p_machines, p_tasks, ecs, seed=3)
            pl = RoundPlanner(st, get_cost_model("cpu_mem"))
            _, m = pl.schedule_round()
        finally:
            os.environ["POSEIDON_SHARDED_BANDS"] = "1"
            os.environ.pop("POSEIDON_SHARDED_MIN_COLS", None)
            os.environ.pop("POSEIDON_SHARDED_MIN_CONTENTION", None)
        return m

    p_machines, p_tasks = min(machines, 4_000), min(tasks, 40_000)
    m_sh = _parity_round(sharded=True)
    m_dn = _parity_round(sharded=False)
    parity_ok = bool(
        m_sh.solve_tier == "sharded"
        and m_sh.objective == m_dn.objective
        and m_sh.placed == m_dn.placed
        and m_sh.gap_bound == 0.0 and m_dn.gap_bound == 0.0
    )
    partial.update(
        parity_machines=p_machines, parity_tasks=p_tasks,
        parity_sharded_tier=m_sh.solve_tier,
        parity_dense_tier=m_dn.solve_tier,
        parity_objective=int(m_sh.objective),
        parity_dense_objective=int(m_dn.objective),
        sharded_parity_ok=parity_ok,
        partial="after reduced-scale parity",
    )
    print(json.dumps(partial), flush=True)
    if verbose:
        print(f"# [cluster] parity {p_machines}/{p_tasks}: "
              f"sharded={m_sh.objective} ({m_sh.solve_tier}) "
              f"dense={m_dn.objective} ok={parity_ok}", file=sys.stderr)

    # ---- saturation leg: capacities/supplies at the int32 cliff must
    # trip the dispatch certificate, the telemetry saturation flag, or
    # the numerics ledger — never wrap silently.  Tiny instance (the
    # hazard is aggregate magnitude, not matrix width), so the leg
    # costs seconds at any rung scale.
    saturation = run_saturation_probe()
    partial.update(
        saturation=saturation, partial="after saturation probe"
    )
    print(json.dumps(partial), flush=True)
    if verbose:
        print(f"# [cluster] saturation: cert="
              f"{saturation['certificate_tripped']} "
              f"sat_samples={saturation['saturated_samples']} "
              f"anomalies={saturation['ledger_anomalies']} "
              f"ok={saturation['ok']}", file=sys.stderr)

    # ---- the cluster-scale rung itself.
    state = build_cluster(machines, tasks, ecs, seed=0)
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    t0 = time.perf_counter()
    _, metrics = planner.schedule_round()
    cold_s = time.perf_counter() - t0
    converged = metrics.converged
    partial.update(
        cold_s=round(cold_s, 4), cold_tier=metrics.solve_tier,
        partial="after cold round",
    )
    print(json.dumps(partial), flush=True)
    if verbose:
        print(f"# [cluster] cold: {cold_s:.3f}s tier={metrics.solve_tier} "
              f"placed={metrics.placed} unsched={metrics.unscheduled} "
              f"shards={metrics.shard_devices}", file=sys.stderr)

    def _shard_lanes():
        # Per-shard excess totals of the round's dominant sharded curve
        # (the artifact's per-device work split; the full downsampled
        # lanes ride the round history / flight recorder).
        curves = [c for c in planner.last_solve_curves
                  if c.get("shard_excess")]
        if not curves:
            return []
        dom = max(curves, key=lambda c: c.get("samples", 0))
        return [int(sum(lane)) for lane in dom["shard_excess"]]

    wave_lat, churn_lat = [], []
    wave_device_calls, wave_solve_iters = [], []
    wave_sharded_bands, wave_shard_imbalance = [], []
    solve_tiers = {metrics.solve_tier}
    shard_lanes = _shard_lanes()
    rng = np.random.default_rng(12345)
    placed = unsched = objective = 0
    for r in range(rounds):
        # Cluster-scale steady state is churn, not drain/resubmit: a
        # fresh 1M-task wave per round would make the rung all host
        # submission overhead (and the cold round above already IS the
        # full wave).
        churn_step(state, rng, frac=1000)
        t0 = time.perf_counter()
        _, metrics = planner.schedule_round()
        dt = time.perf_counter() - t0
        churn_lat.append(dt)
        wave_device_calls.append(metrics.device_calls)
        wave_solve_iters.append(metrics.iterations)
        wave_sharded_bands.append(metrics.sharded_bands)
        wave_shard_imbalance.append(metrics.shard_imbalance)
        solve_tiers.add(metrics.solve_tier)
        shard_lanes = _shard_lanes() or shard_lanes
        placed, unsched = metrics.placed, metrics.unscheduled
        objective = metrics.objective
        converged = converged and metrics.converged
        if verbose:
            print(f"# [cluster] churn {r}: {dt:.3f}s "
                  f"tier={metrics.solve_tier} iters={metrics.iterations} "
                  f"calls={metrics.device_calls} "
                  f"imbalance={metrics.shard_imbalance}", file=sys.stderr)
        partial.update(
            churn_p50_s=round(float(np.percentile(churn_lat, 50)), 4),
            partial=f"after churn {r + 1}/{rounds}",
        )
        print(json.dumps(partial), flush=True)

    return {
        "machines": machines,
        "tasks": tasks,
        "backend": backend,
        "devices": n_dev,
        "cold_s": round(cold_s, 4),
        "churn_p50_s": (
            round(float(np.percentile(churn_lat, 50)), 4)
            if churn_lat else None
        ),
        "parity_machines": p_machines,
        "parity_tasks": p_tasks,
        "parity_objective": int(m_sh.objective),
        "parity_dense_objective": int(m_dn.objective),
        "sharded_parity_ok": parity_ok,
        "saturation": saturation,
        # Per-device work series (machine-independent counts).
        "device_calls": wave_device_calls,
        "solve_iters": wave_solve_iters,
        "sharded_bands": wave_sharded_bands,
        "shard_imbalance": wave_shard_imbalance,
        "shard_excess_totals": shard_lanes,
        "solve_tiers": sorted(solve_tiers),
        "placed": placed,
        "unscheduled": unsched,
        "objective": objective,
        "converged": converged,
        "ok": bool(parity_ok and converged and saturation["ok"]),
    }


def build_artifact(rungs, target, parity, trace, features,
                   cluster=None, throughput=None, scenario=None) -> dict:
    """The scored JSON line the driver records.

    Scores ONLY the target config (the north star, or the requested
    config in single-config mode): a bench that loses rungs to a
    timeout must post a WORSE artifact, never a better-looking one
    (round-4 review: "largest completed rung" scoring rewarded
    timeouts).  An unconverged target rung posts no vs_baseline:
    budget-exhausted solves return fast but commit uncertified
    placements, and claiming a win on them would be dishonest.
    Module-level and pure so tests can pin the scoring contract.
    """
    best = None
    for r in rungs:
        if (r.get("ok")
                and (r.get("machines"), r.get("tasks")) == target):
            best = r
    out = {
        "metric": "schedule_round_s",
        "unit": "s",
        "target_machines": target[0],
        "target_tasks": target[1],
        # Parity failure and parity-harness failure are different
        # triage paths: surface the whole child result, not the bit.
        "parity_ok": parity.get("parity_ok", False),
        "parity": parity,
        "trace": trace,
        # BASELINE configs 2-4: selectors / pod affinity / gang, with
        # semantic predicates (violations must be zero) next to the
        # latency numbers.
        "features": features,
        "ladder": rungs,
    }
    if cluster is not None:
        # The opt-in cluster-scale rung (CLUSTER_RUNG): sharded-tier
        # wave + churn with its own reduced-scale parity verdict and
        # per-device work series.  Not the scored number — the north
        # star stays the target config above.
        out["cluster"] = cluster
    if throughput is not None:
        # The sustained-throughput rung (streaming round engine).  Its
        # ``mode`` marker rides to the top so tools/bench_compare.py can
        # refuse to diff streaming series against a synchronous-mode
        # baseline artifact.
        out["throughput"] = throughput
        if throughput.get("mode"):
            out["mode"] = throughput["mode"]
    if scenario is not None:
        # The scenario rung (trace-driven production-shaped workloads):
        # per-scenario throughput, robustness-under-cost-perturbation,
        # and staleness series for tools/bench_compare.py.  Mode marker
        # as above — its identity legs drive the streaming engine.
        out["scenario"] = scenario
        if scenario.get("mode") and "mode" not in out:
            out["mode"] = scenario["mode"]
    if best is None:
        out.update({"value": None, "vs_baseline": 0.0,
                    "error": f"target rung {target[0]}/{target[1]} "
                             "not completed"})
    else:
        # Headline: a full pending wave at the north-star config
        # (BASELINE.md: "10k nodes / 100k pending pods round < 1 s").
        # Steady-state churn p50 is reported alongside (the latency a
        # production cluster pays every round) but does not set the
        # score.
        value = best["wave_p50_s"]
        honest = bool(best.get("converged"))
        out.update({
            "value": value,
            "vs_baseline": (
                round(1.0 / value, 3) if honest and value > 0 else 0.0
            ),
            "converged": best.get("converged"),
            "machines": best["machines"],
            "tasks": best["tasks"],
            "backend": best.get("backend"),
            "cold_s": best["cold_s"],
            "wave_p50_s": best["wave_p50_s"],
            "churn_p50_s": best["churn_p50_s"],
            # Recovery-to-first-placement after a checkpoint restore
            # at the scored scale (the warm frames ride the
            # checkpoint; the reference has no counterpart).
            "restart_s": best.get("restart_round_s"),
        })
        # Per-round DEVICE-WORK series of the scored rung, lifted to the
        # top level so tools/bench_compare.py gates them from wrapper
        # artifacts too (they are machine-independent — the wall timings
        # above are not).
        for key in ("wave_solve_iters", "wave_bf_sweeps",
                    "wave_device_calls", "wave_entry_phase",
                    "wave_telem_samples", "wave_telem_iters_to_90",
                    "wave_sharded_bands", "wave_shard_imbalance",
                    "solve_tiers",
                    "churn_solve_iters", "churn_device_calls",
                    "churn_delta_hits"):
            if key in best:
                out[key] = best[key]
    return out


def _load_last_live_tpu(target):
    """Most recent committed live-TPU rung at ``target`` from
    ``out/tpu_bench.jsonl``, or None.

    Evidence pointer, NEVER the score: when a bench run cannot reach
    the accelerator (dead tunnel / dead compile service), the driver
    attaches this to the artifact so the record of a hardware-validated
    north-star number travels with it; the score fields reflect only
    what the run itself measured.  Called ONCE per run by the driver —
    ``build_artifact`` stays pure (the scoring tests depend on that).
    Lines are scanned newest-first: the file holds one superset line
    per completed stage, and a later capture that died before its 10k
    rung must not hide an earlier line's completed one."""
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "out", "tpu_bench.jsonl"
    )
    try:
        with open(path, encoding="utf-8") as fh:
            lines = [ln for ln in fh if ln.startswith("{")]
    except Exception:  # noqa: BLE001 - evidence is optional; never fatal
        return None
    for ln in reversed(lines):
        try:  # one corrupt line must not hide older good ones
            for r in json.loads(ln).get("ladder", []):
                if (r.get("backend") == "tpu" and r.get("ok")
                        and (r.get("machines"), r.get("tasks"))
                        == tuple(target)):
                    return {"mtime": int(os.path.getmtime(path)), **r}
        except Exception:  # noqa: BLE001
            continue
    return None


def _child(mode: str, argv: list, timeout: int) -> dict:
    """Run one rung/parity in a subprocess; never raises.

    Timeout discipline: SIGTERM first (the child's handler exits after
    the in-flight device op completes — never mid-op), then a long grace,
    then SIGKILL only for a child already hung inside a wedged tunnel.
    """
    cmd = [sys.executable, os.path.abspath(__file__), "--child", mode] + argv
    try:
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        timed_out = False
        try:
            out, err = proc.communicate(timeout=timeout + _prework_allowance())
        except subprocess.TimeoutExpired:
            timed_out = True
            proc.terminate()
            try:
                out, err = proc.communicate(timeout=term_grace_s())
            except subprocess.TimeoutExpired:
                print(f"# child {mode} ignored SIGTERM for {term_grace_s()}s "
                      "(wedged tunnel?); escalating to SIGKILL",
                      file=sys.stderr)
                proc.kill()
                out, err = proc.communicate()
        sys.stderr.write(err)
        # Children print a JSON line per completed stage, so even a
        # timed-out child usually leaves partial measurements on stdout —
        # salvage the last one instead of discarding the whole stage.
        last = None
        for line in reversed(out.splitlines()):
            if line.startswith("{"):
                try:
                    last = json.loads(line)
                except json.JSONDecodeError:
                    continue  # a line truncated by the kill
                break
        if timed_out:
            if last is None:
                return {"ok": False, "error": f"timeout after {timeout}s"}
            # Children mark their own partiality ("partial"/ok fields):
            # a rung's stage lines carry ok=False until the rung
            # finishes, while the trace child's pre-pressure line is a
            # complete, valid main-replay result — don't overwrite it.
            last["timed_out"] = f"timeout after {timeout}s"
            return last
        if last is not None:
            if proc.returncode != 0:
                # Crashed after printing partial lines: keep the numbers
                # but carry the failure diagnostic the artifact needs.
                last["ok"] = False
                last.setdefault(
                    "error", f"child exited rc={proc.returncode} "
                    "after partial results"
                )
            return last
        return {"ok": False,
                "error": f"rc={proc.returncode}, no JSON in child output"}
    except Exception as e:  # noqa: BLE001 - the artifact must always emit
        return {"ok": False, "error": repr(e)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--machines", type=int, default=0,
                   help="single-config mode (skips the ladder)")
    p.add_argument("--tasks", type=int, default=0)
    p.add_argument("--ecs", type=int, default=100)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--child",
                   choices=["rung", "parity", "trace", "features", "soak",
                            "cluster", "throughput", "scenario"],
                   default=None)
    p.add_argument("--seconds", type=float, default=6.0,
                   help="fixed duration for --child throughput's "
                        "continuous-churn legs")
    p.add_argument("--cluster", action="store_true",
                   help="also run the opt-in cluster-scale rung "
                        "(CLUSTER_RUNG; sharded band tier)")
    p.add_argument("--plan", default="smoke",
                   help="fault plan name for --child soak")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    if args.child == "cluster":
        # The sharded tier needs a device mesh: on host-only backends
        # force a virtual one BEFORE jax initializes (a no-op when the
        # flag is already present or a real multi-device backend is
        # attached).
        flags = os.environ.get("XLA_FLAGS", "")
        if ("xla_force_host_platform_device_count" not in flags
                and os.environ.get("JAX_PLATFORMS", "") == "cpu"):
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    if args.child is not None:
        _ensure_live_backend()
        # Persistent compile cache: rung/trace children each start a fresh
        # process; without it every child repeats the full compile storm.
        from poseidon_tpu.utils.envutil import (
            enable_compilation_cache,
            install_graceful_term,
        )

        enable_compilation_cache()
        install_graceful_term()
    if args.child == "rung":
        print(json.dumps(run_rung(args.machines, args.tasks, args.ecs,
                                  args.rounds, args.verbose)))
        return 0
    if args.child == "parity":
        print(json.dumps(run_parity()))
        return 0
    if args.child == "trace":
        print(json.dumps(run_trace(args.machines, args.tasks, args.rounds)))
        return 0
    if args.child == "features":
        print(json.dumps(run_features(args.machines, args.rounds)))
        return 0
    if args.child == "soak":
        print(json.dumps(run_soak(
            args.machines or 200, max(args.rounds, 8), args.plan, args.seed
        )))
        return 0
    if args.child == "throughput":
        print(json.dumps(run_throughput(
            args.machines or 64, args.seconds, args.seed
        )))
        return 0
    if args.child == "scenario":
        print(json.dumps(run_scenario(
            args.machines or 16, max(args.rounds, 6), args.seed
        )))
        return 0
    if args.child == "cluster":
        print(json.dumps(run_cluster_rung(
            args.machines or CLUSTER_RUNG[0],
            args.tasks or CLUSTER_RUNG[1],
            args.ecs, args.rounds, args.verbose,
        )))
        return 0

    # ---- parent: drive the stages; never touches jax (the probe runs in
    # a disposable subprocess), and re-emits the running JSON line after
    # EVERY stage, so even if this process is killed mid-ladder the last
    # line on stdout is a valid artifact for everything completed so far
    # (a line-scanning consumer takes the final line; each line is a
    # superset of the previous one).
    _parent_probe_and_latch()
    ladder = LADDER
    target = NORTH_STAR
    if args.machines:
        ladder = [(args.machines, args.tasks or 10 * args.machines)]
        target = ladder[0]
    rungs = []
    parity = {"ok": False, "error": "not run"}
    trace = {"ok": False, "error": "not run"}
    features = {"ok": False, "error": "not run"}
    cluster = None
    throughput = None
    scenario = None

    live_evidence = _load_last_live_tpu(target)  # once; None when absent

    def emit():
        art = build_artifact(rungs, target, parity, trace, features,
                             cluster=cluster, throughput=throughput,
                             scenario=scenario)
        if art.get("backend") != "tpu" and live_evidence is not None:
            art["last_live_tpu"] = live_evidence
        print(json.dumps(art), flush=True)

    def _stage(mode, argv, timeout):
        """One bench stage with the mid-ladder backend recheck: a stage
        that fails while the accelerator verdict is latched triggers one
        re-probe, and a dead backend retries the stage once on CPU."""
        res = _child(mode, argv, timeout)
        if _stage_failed_recheck(res):
            res = _child(mode, argv, timeout)
        return res

    def run_rung_child(machines, tasks):
        res = _stage("rung", [
            "--machines", str(machines), "--tasks", str(tasks),
            "--ecs", str(args.ecs), "--rounds", str(args.rounds),
        ] + (["--verbose"] if args.verbose else []), rung_timeout_s())
        res.setdefault("machines", machines)
        res.setdefault("tasks", tasks)
        rungs.append(res)
        emit()
        if not res.get("ok"):
            print(f"# rung {machines}/{tasks} failed: "
                  f"{res.get('error')}; continuing with remaining rungs",
                  file=sys.stderr)
        return res

    emit()  # a valid (empty-ladder) line exists before any child runs
    parity = _stage("parity", [], PARITY_TIMEOUT_S)
    emit()

    # North-star rung FIRST: it is the scored number and must get the
    # freshest budget.  Then the trace replay (BASELINE config 5) — ahead
    # of the scaling-table rungs, which round 4 lost to an outer timeout.
    first = run_rung_child(*ladder[0])
    if first.get("ok"):
        t_machines, t_tasks = first["machines"], first["tasks"]
    elif args.machines:
        # Single-config smokes never pay an unrequested scale: a failed
        # requested rung sizes the trace at the requested config anyway
        # (its own timeout bounds it).
        t_machines, t_tasks = ladder[0]
    else:
        t_machines, t_tasks = 1_000, 10_000  # modest, completable sizing
    trace = _stage("trace", [
        "--machines", str(t_machines), "--tasks", str(t_tasks),
        "--rounds", str(max(args.rounds * 4, 12)),
    ], rung_timeout_s())
    emit()
    if not args.machines:
        # Full-ladder mode only: single-config runs are quick focused
        # smokes and must not pay an unrequested cluster-scale stage.
        # NORTH-STAR scale (round-4 review asked 4k, 10k if budget
        # allows; the round-5 wave/churn work made 10k cost ~45 s warm):
        # the reference's behavior claims are cluster-scale claims, and
        # the semantic predicates (zero violations, whole gangs) now
        # hold at the scale the project's headline claims.
        features = _stage("features", [
            "--machines", "10000", "--rounds", "3",
        ], features_timeout_s())
        emit()
        # Sustained-throughput rung: streaming vs synchronous through
        # the full glue+service stack at modest scale (the number is a
        # RATIO claim — overlap gain — not a scale claim, so it never
        # pays ladder-sized machine counts).
        throughput = _stage("throughput", [
            "--machines", "64", "--seconds", "6",
            "--seed", str(args.seed),
        ], rung_timeout_s())
        emit()
        # Scenario rung: the named production-shaped workloads, both
        # loop modes + robustness scoring.  ~5 scenarios x (2 identity
        # drives + N perturbed re-drives) full-stack sessions, so it
        # gets a doubled child budget; like throughput it is a behavior
        # claim and stays at modest scale.
        scenario = _stage("scenario", [
            "--machines", "16", "--rounds", "6",
            "--seed", str(args.seed),
        ], rung_timeout_s() * 2)
        emit()
    for machines, tasks in ladder[1:]:
        run_rung_child(machines, tasks)
    if args.cluster:
        # Last on purpose: the cluster-scale rung must never starve the
        # scored rungs' budget, and its own partial-line protocol means
        # a timeout still posts the parity verdict + completed rounds.
        cluster = _stage("cluster", [
            "--rounds", "1",
        ] + (["--verbose"] if args.verbose else []), rung_timeout_s() * 2)
        emit()
    return 0


if __name__ == "__main__":
    sys.exit(main())
