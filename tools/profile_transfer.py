"""Separate the tunnel's per-transfer latency from its bandwidth.

The round-5 live profile (out/tpu_profile_1k.txt) showed a [100x1000]
i32 upload at ~60 ms and download at ~116 ms — either a ~60 ms/transfer
round-trip floor (cure: FEWER transfers — batch operands, device-resident
state) or a ~3-7 MB/s pipe (cure: SMALLER transfers — narrow dtypes,
compact results).  This probe times device_put / np.asarray across a
size ladder and fits time = latency + bytes/bandwidth, and also measures
whether N separate small buffers cost N round trips or one (the operand-
batching question: a solve ships ~8 operands per dispatch).

Usage: python tools/profile_transfer.py [--reps 7]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def p50(xs):
    return float(np.percentile(xs, 50))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=7)
    args = ap.parse_args()

    from poseidon_tpu.utils.envutil import (
        probe_device_count,
        serialize_device_access,
    )

    if not serialize_device_access():
        print("device lock busy; not contending for the accelerator",
              flush=True)
        raise SystemExit(2)
    if probe_device_count(timeout=300.0) < 0:
        print("backend unreachable (wedged tunnel?); aborting", flush=True)
        raise SystemExit(2)

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"backend: {jax.default_backend()} ({dev.device_kind})",
          flush=True)

    # --- size ladder: one buffer per transfer --------------------------
    sizes = [(8, 128), (64, 512), (100, 1000), (256, 2048),
             (256, 10240), (512, 10240)]
    rows = []
    for (e, m) in sizes:
        x = np.arange(e * m, dtype=np.int32).reshape(e, m)
        ups, downs = [], []
        for _ in range(args.reps):
            t0 = time.perf_counter()
            xd = jax.device_put(x, dev)
            xd.block_until_ready()
            ups.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            np.asarray(xd)
            downs.append(time.perf_counter() - t0)
        mb = x.nbytes / 1e6
        rows.append((mb, p50(ups), p50(downs)))
        print(f"[{e}x{m}] {mb:7.2f} MB  up p50 {p50(ups)*1e3:8.1f} ms"
              f"  down p50 {p50(downs)*1e3:8.1f} ms", flush=True)

    # Least-squares fit time = a + b*MB on the p50s.
    A = np.vstack([np.ones(len(rows)), [r[0] for r in rows]]).T
    for name, col in (("upload", 1), ("download", 2)):
        coef, *_ = np.linalg.lstsq(A, [r[col] for r in rows], rcond=None)
        lat_ms, s_per_mb = coef[0] * 1e3, coef[1]
        bw = (1.0 / s_per_mb) if s_per_mb > 1e-9 else float("inf")
        print(f"{name}: latency ~{lat_ms:.1f} ms/transfer, "
              f"bandwidth ~{bw:.1f} MB/s", flush=True)

    # --- operand batching: 8 small buffers vs 1 equal-size buffer ------
    n_ops = 8
    small = [np.arange(100 * 1000, dtype=np.int32).reshape(100, 1000)
             for _ in range(n_ops)]
    big = np.arange(n_ops * 100 * 1000, dtype=np.int32)
    many, one = [], []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        ds = [jax.device_put(s, dev) for s in small]
        for d in ds:
            d.block_until_ready()
        many.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        jax.device_put(big, dev).block_until_ready()
        one.append(time.perf_counter() - t0)
    print(f"{n_ops} x 0.4 MB buffers p50 {p50(many)*1e3:.1f} ms vs "
          f"one {big.nbytes/1e6:.1f} MB buffer p50 {p50(one)*1e3:.1f} ms",
          flush=True)

    # --- does a dispatch on device-RESIDENT operands avoid the floor? --
    f = jax.jit(lambda a, b: (a + b).sum())
    xd = jax.device_put(small[0], dev)
    yd = jax.device_put(small[0], dev)
    f(xd, yd).block_until_ready()           # compile
    resident, from_host = [], []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        f(xd, yd).block_until_ready()
        resident.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        f(small[0], small[0]).block_until_ready()
        from_host.append(time.perf_counter() - t0)
    print(f"jit on resident operands p50 {p50(resident)*1e3:.1f} ms; "
          f"same jit fed numpy p50 {p50(from_host)*1e3:.1f} ms", flush=True)


if __name__ == "__main__":
    main()
