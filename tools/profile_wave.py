"""Per-stage decomposition of a full schedule round at scale, on the
live backend: host prep vs tunnel transfers vs in-program device time
vs assignment/commit.  This is the measurement that picks between the
wave's two remaining levers (single-dispatch band fusion vs host-path
cuts) — run it on the real TPU before touching either.

Usage (serialize against other chip users; never external-kill):
    python tools/profile_wave.py [--machines 10000] [--tasks 100000]
                                 [--waves 4] [--churn 3]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--machines", type=int, default=10000)
    ap.add_argument("--tasks", type=int, default=100000)
    ap.add_argument("--ecs", type=int, default=100)
    ap.add_argument("--waves", type=int, default=4)
    ap.add_argument("--churn", type=int, default=3)
    args = ap.parse_args()

    os.environ["POSEIDON_STAGE_TIMERS"] = "1"

    from poseidon_tpu.utils.envutil import (
        enable_compilation_cache,
        probe_device_count,
        serialize_device_access,
    )

    if not serialize_device_access():
        print("device lock busy; aborting", flush=True)
        raise SystemExit(2)
    if probe_device_count(timeout=300.0) < 0:
        print("backend unreachable; aborting", flush=True)
        raise SystemExit(2)
    enable_compilation_cache()

    import jax

    from bench import build_cluster, submit_population
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.utils import stagetimer

    print(f"backend: {jax.devices()[0].platform}", flush=True)
    M, T, E = args.machines, args.tasks, args.ecs
    state = build_cluster(M, T, E, seed=0)
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))

    t0 = time.perf_counter()
    planner.schedule_round()
    print(f"cold: {time.perf_counter() - t0:.2f}s", flush=True)
    t0 = time.perf_counter()
    shapes = planner.precompile(max_ecs=256)
    print(f"precompile: {shapes} shapes {time.perf_counter() - t0:.1f}s",
          flush=True)

    stagetimer.reset()
    wave_lat = []
    for r in range(args.waves):
        for uid in list(state.tasks.keys()):
            state.task_removed(uid)
        submit_population(state, T, E, seed=r + 1)
        t0 = time.perf_counter()
        _, m = planner.schedule_round()
        dt = time.perf_counter() - t0
        wave_lat.append(dt)
        print(f"wave {r}: {dt:.3f}s solve={m.solve_seconds:.3f}s "
              f"iters={m.iterations} calls={m.device_calls}", flush=True)
    print(f"\n== WAVE stage table ({args.waves} waves, p50 wall "
          f"{float(np.percentile(wave_lat, 50)):.3f}s) ==")
    print(stagetimer.report(), flush=True)

    stagetimer.reset()
    rng = np.random.default_rng(99)
    churn_lat = []
    for r in range(args.churn):
        uids = list(state.tasks.keys())
        for uid in rng.choice(len(uids), size=max(T // 100, 1),
                              replace=False):
            state.task_removed(uids[int(uid)])
        submit_population(state, max(T // 100, 1), E, seed=1000 + r)
        t0 = time.perf_counter()
        planner.schedule_round()
        churn_lat.append(time.perf_counter() - t0)
        print(f"churn {r}: {churn_lat[-1]:.3f}s", flush=True)
    print(f"\n== CHURN stage table ({args.churn} rounds, p50 wall "
          f"{float(np.percentile(churn_lat, 50)):.3f}s) ==")
    print(stagetimer.report(), flush=True)


if __name__ == "__main__":
    main()
