"""Decompose round latency: tunnel dispatch overhead vs device compute.

The production TPU is reached through a tunnel (platform "axon"), so every
jitted call pays a host<->device network round trip on top of the device
program.  This script measures, on whatever backend is live:

1. ``dispatch_us``: round-trip of a trivial jitted op (the pure tunnel+
   runtime floor) — p50 over N calls;
2. ``transfer``: host->device + device->host time for the [E, M] operand
   set a band solve ships;
3. ``solve``: end-to-end wall time of one warm ``solve_transport`` call at
   a churn-representative shape, plus its iteration count — giving
   device-time-per-iteration once (1) and (2) are subtracted.

Usage: python tools/profile_solver.py [--machines 1000] [--ecs 100]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def p50(xs):
    return float(np.percentile(xs, 50))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--machines", type=int, default=1000)
    ap.add_argument("--ecs", type=int, default=100)
    ap.add_argument("--reps", type=int, default=20)
    args = ap.parse_args()

    # Never-hang posture: take the host-wide device lock (concurrent
    # backend init wedges the tunnel), then probe in a disposable
    # subprocess before committing this process to the first jax touch.
    from poseidon_tpu.utils.envutil import (
        probe_device_count,
        serialize_device_access,
    )

    if not serialize_device_access():  # $POSEIDON_DEVICE_LOCK_TIMEOUT
        print("device lock busy; not contending for the accelerator",
              flush=True)
        raise SystemExit(2)
    if probe_device_count(timeout=300.0) < 0:
        print("backend unreachable (wedged tunnel?); aborting", flush=True)
        raise SystemExit(2)

    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev})", flush=True)

    # 1. trivial dispatch round-trip
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((), jnp.int32)
    f(x).block_until_ready()
    ts = []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        ts.append(time.perf_counter() - t0)
    print(f"dispatch p50: {p50(ts)*1e6:.0f} us  (min {min(ts)*1e6:.0f} us)")

    # 1b. while-step overhead: a jitted loop of N trivial iterations.
    # On TPU each lax.while_loop step pays a fixed sync/predicate cost;
    # this measures it directly (drives the unroll-factor decisions).
    from jax import lax

    def loop(n):
        def body(st):
            x, i = st
            return x + 1, i + 1

        def cond(st):
            return st[1] < n

        return lax.while_loop(cond, body, (jnp.int32(0), jnp.int32(0)))[0]

    jloop = jax.jit(loop)
    jloop(jnp.int32(1)).block_until_ready()
    for n in (1000, 10000):
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            jloop(jnp.int32(n)).block_until_ready()
            ts.append(time.perf_counter() - t0)
        print(f"while_loop {n} steps p50: {p50(ts)*1e3:.1f} ms "
              f"({p50(ts)/n*1e6:.2f} us/step)", flush=True)

    # 2. operand transfer for a band-solve-sized instance
    E, M = args.ecs, args.machines
    rng = np.random.default_rng(0)
    costs = rng.integers(0, 1000, size=(E, M)).astype(np.int32)
    ts_up, ts_down = [], []
    for _ in range(args.reps):
        t0 = time.perf_counter()
        d = jax.device_put(costs).block_until_ready()
        ts_up.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        np.asarray(d)
        ts_down.append(time.perf_counter() - t0)
    print(f"[{E}x{M}] i32 upload p50: {p50(ts_up)*1e3:.2f} ms, "
          f"download p50: {p50(ts_down)*1e3:.2f} ms")

    # 3. one solve at churn-representative shape, warm (pre-compiled)
    from poseidon_tpu.ops.transport import solve_transport

    supply = rng.integers(1, 8, size=E).astype(np.int32)
    capacity = rng.integers(8, 64, size=M).astype(np.int32)
    unsched = np.full(E, 2000, dtype=np.int32)
    sol = solve_transport(costs, supply, capacity, unsched)  # compile
    ts = []
    iters = sol.iterations
    for _ in range(max(args.reps // 4, 3)):
        t0 = time.perf_counter()
        sol = solve_transport(costs, supply, capacity, unsched)
        ts.append(time.perf_counter() - t0)
    t_solve = p50(ts)
    print(f"solve[{E}x{M}] p50: {t_solve*1e3:.1f} ms, "
          f"iters={sol.iterations} "
          f"(~{t_solve/max(sol.iterations,1)*1e6:.0f} us/iter incl. "
          "dispatch+transfer)")

    # 4. same solve, warm-started with its own solution (few iterations):
    # isolates the fixed per-call cost at this shape.
    ts = []
    for _ in range(max(args.reps // 4, 3)):
        t0 = time.perf_counter()
        sol2 = solve_transport(
            costs, supply, capacity, unsched, sol.prices,
            init_flows=sol.flows, init_unsched=sol.unsched, eps_start=1,
        )
        ts.append(time.perf_counter() - t0)
    print(f"warm-identical solve p50: {p50(ts)*1e3:.1f} ms, "
          f"iters={sol2.iterations}  <- fixed per-call floor at this shape")


if __name__ == "__main__":
    main()
