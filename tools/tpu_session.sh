#!/usr/bin/env bash
# One serialized TPU measurement session, to run when the tunnel is
# alive.  Order matters: cheap validation first, the expensive ladder
# last, everything through ONE process at a time (the flock in
# envutil.serialize_device_access); never externally kill any step —
# each step bounds itself internally.
set -uo pipefail
cd "$(dirname "${BASH_SOURCE[0]}")/.."
mkdir -p out

echo "=== 1. latency decomposition (tunnel dispatch / transfer / solve)"
python tools/profile_solver.py --machines 1000 --ecs 100 2>&1 | tee out/tpu_profile_1k.txt

echo "=== 2. fused-kernel Mosaic validation + A/B vs lax path"
python tools/bench_fused.py 2>&1 | tee out/tpu_fused_ab.txt

echo "=== 3. full bench ladder (tagged backend; partial lines salvage)"
POSEIDON_BENCH_RUNG_TIMEOUT="${POSEIDON_BENCH_RUNG_TIMEOUT:-3000}" \
python bench.py --verbose 2> >(tee out/tpu_bench_stderr.txt >&2) | tee out/tpu_bench.jsonl

echo "=== done; last bench line:"
tail -1 out/tpu_bench.jsonl
