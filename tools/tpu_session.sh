#!/usr/bin/env bash
# One serialized TPU measurement session, to run when the tunnel is
# alive.  Order matters: cheap validation first, the expensive ladder
# last, everything through ONE process at a time (the flock in
# envutil.serialize_device_access); never externally kill any step —
# each step bounds itself internally.
#
# TPU_SESSION_DRYRUN=1 reruns the exact same step sequence on a clean
# CPU environment (accelerator plugin stripped, smoke-sized configs) so
# the script's own plumbing — paths, flags, tee targets, JSON parsing —
# is proven BEFORE it meets scarce live-tunnel time.  Only the
# TPU-specific lines (Mosaic lowering, real dispatch costs) remain
# unproven after a green dry run.
set -uo pipefail
cd "$(dirname "${BASH_SOURCE[0]}")/.."
mkdir -p out

SUFFIX=""
if [ "${TPU_SESSION_DRYRUN:-}" = "1" ]; then
  echo "=== DRY RUN: clean-CPU environment, smoke-sized configs ==="
  SUFFIX=".dryrun"
  # The env var alone is not enough when the accelerator site hook is
  # present (it re-pins the platform and hangs on a dead tunnel):
  # strip the plugin the same way envutil.clean_cpu_env does.
  export JAX_PLATFORMS=cpu
  unset PALLAS_AXON_POOL_IPS 2>/dev/null || true
  PYTHONPATH="$(python - <<'EOF'
import os
print(os.pathsep.join(
    [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
     if p and "axon" not in p] + [os.getcwd()]))
EOF
)"
  export PYTHONPATH
  export POSEIDON_BENCH_FUSED_SMOKE=1
  PROFILE_ARGS="--machines 200 --ecs 32"
  WAVE_ARGS="--machines 200 --tasks 2000 --waves 2 --churn 2"
  TRANSFER_ARGS="--reps 2"
  BENCH_ARGS="--machines 200 --tasks 2000 --rounds 2"
else
  PROFILE_ARGS="--machines 1000 --ecs 100"
  WAVE_ARGS="--machines 10000 --tasks 100000 --waves 4 --churn 3"
  TRANSFER_ARGS=""
  BENCH_ARGS="--verbose"
fi

echo "=== 1. latency decomposition (tunnel dispatch / transfer / solve)"
python tools/profile_solver.py $PROFILE_ARGS 2>&1 | tee "out/tpu_profile_1k.txt$SUFFIX"

echo "=== 2. transfer scaling (latency vs bandwidth fit)"
python tools/profile_transfer.py $TRANSFER_ARGS 2>&1 | tee "out/tpu_transfer.txt$SUFFIX"

echo "=== 3. fused-kernel Mosaic validation + A/B vs lax path"
python tools/bench_fused.py 2>&1 | tee "out/tpu_fused_ab.txt$SUFFIX"

echo "=== 4. wave/churn stage split at the north star (per-band path)"
python tools/profile_wave.py $WAVE_ARGS 2>&1 | tee "out/tpu_wave_stages.txt$SUFFIX"

echo "=== 4b. same, CHAINED single-dispatch wave (the live A/B that decides its default)"
POSEIDON_CHAINED=1 python tools/profile_wave.py $WAVE_ARGS 2>&1 | tee "out/tpu_wave_chained.txt$SUFFIX"

echo "=== 4c. same, host-seeded per-band path (fused pipeline OFF): true"
echo "===     iteration counts are comparable (the old 3-4x was a metrics"
echo "===     accounting artifact) - this arm prices the 2 extra dispatches"
echo "===     against the one-program execution on real hardware"
POSEIDON_COARSE_FUSED=0 python tools/profile_wave.py $WAVE_ARGS 2>&1 | tee "out/tpu_wave_hostseed.txt$SUFFIX"

echo "=== 5. full bench ladder (tagged backend; partial lines salvage)"
POSEIDON_BENCH_RUNG_TIMEOUT="${POSEIDON_BENCH_RUNG_TIMEOUT:-3000}" \
python bench.py $BENCH_ARGS 2> >(tee "out/tpu_bench_stderr.txt$SUFFIX" >&2) | tee "out/tpu_bench.jsonl$SUFFIX"

echo "=== done; last bench line:"
tail -1 "out/tpu_bench.jsonl$SUFFIX"
