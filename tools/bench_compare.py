"""Perf-regression gate: diff a fresh bench artifact against a baseline.

``make perf-gate`` runs this against the committed round baseline
(BENCH_r05.json, falling back to docs/bench_r05_final.json — the
driver-wrapper format truncates its embedded JSON).  Every overlapping
TIMING series — the headline wave/churn p50s, the restart-recovery
round, and the features stages' per-stage decomposition (mask build /
cost build / solve / view build, the ``stagetimer`` names the obs
tracer accumulates) — is compared, and the gate fails when the fresh
number exceeds the baseline by more than the tolerance band.

Honesty rules (the same ones bench.py's scoring learned the hard way):

- apples to apples only: timings compare ONLY when both artifacts ran
  the same backend and the same target config — a CPU run is never
  judged against a TPU baseline, and a 200-machine smoke is never
  "faster" than the 10k baseline;
- a missing series in EITHER artifact is reported as skipped, never
  silently dropped from the verdict line;
- tiny stages get an absolute floor: a 3 ms stage doubling to 6 ms is
  measurement noise, not a regression.

Exit codes: 0 = no regressions (or ``--warn-only``), 1 = regression(s),
2 = unusable inputs without ``--warn-only`` (missing/corrupt artifact).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

DEFAULT_TOLERANCE = 0.35   # fail past baseline * (1 + tolerance) ...
DEFAULT_ABS_FLOOR_S = 0.05  # ... and only if the delta clears this floor

# (dotted series name, path into the artifact dict)
_FEATURE_STAGES = (
    "round_s", "round_p50_s", "mask_build_s", "cost_build_s",
    "solve_s", "view_build_s",
)

# Per-round DEVICE-WORK series (run_rung artifacts): summed across
# rounds and gated as counts — machine-independent, so they catch a
# device-work regression wall time hides behind host overlap (and the
# reverse).  Tolerances are looser than the timing band (fresh-wave
# iteration counts vary a few percent run to run through tie-breaks)
# with absolute floors sized to each unit.
_COUNT_SERIES = (
    # (artifact key, tolerance, absolute floor)
    ("wave_solve_iters", 0.5, 64),
    ("wave_bf_sweeps", 0.5, 256),
    ("wave_device_calls", 0.5, 2),
    # Churn series are BIMODAL: a round whose warm start passes the
    # exact host certificate costs 0 iterations, a miss is a genuine
    # ~500-1000-iteration redistribution — and which equally-optimal
    # equilibrium the preceding wave landed on decides the flip.  The
    # band is sized so ONE extra flip over the committed baseline
    # (which already carries one, sum ~1100) passes and two fail —
    # a systemic loss of the zero-dispatch steady state stays caught.
    ("churn_solve_iters", 1.2, 512),
    ("churn_device_calls", 1.2, 3),
)


def load_artifact(path: str) -> Optional[dict]:
    """Parse a bench artifact: a plain JSON object, a ``.jsonl`` stream
    (last parseable object wins — bench.py emits superset lines), or
    the driver wrapper format (``{"parsed": {...}, "tail": "..."}``).
    Returns None when nothing parseable is found."""
    try:
        with open(path, encoding="utf-8") as fh:
            text = fh.read()
    except OSError:
        return None
    objs: List[dict] = []
    for line in text.splitlines():
        if line.startswith("{"):
            try:
                objs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    if not objs:
        try:
            objs.append(json.loads(text))
        except json.JSONDecodeError:
            return None
    art = objs[-1]
    if "metric" not in art and ("parsed" in art or "tail" in art):
        parsed = art.get("parsed")
        if isinstance(parsed, dict):
            return parsed
        tail = art.get("tail", "")
        # The wrapper truncates tail from the FRONT; recoverable only
        # when a whole JSON line survived.
        start = tail.find('{"metric"')
        if start >= 0:
            try:
                return json.loads(tail[start:])
            except json.JSONDecodeError:
                return None
        return None
    return art


def first_artifact(paths: List[str]) -> Tuple[Optional[dict], Optional[str]]:
    for p in paths:
        art = load_artifact(p)
        if art is not None:
            return art, p
    return None, None


def _config_key(art: dict) -> Tuple:
    return (
        art.get("backend"),
        art.get("target_machines", art.get("machines")),
        art.get("target_tasks", art.get("tasks")),
    )


def _mode_key(art: dict) -> str:
    """Round-engine fingerprint for the comparability guard: a
    streaming-mode artifact's series (sustained placements/sec,
    overlap-credited round timings) measure a continuously-overlapped
    loop, not the round-synchronous one — diffing them against a
    synchronous baseline compares two different engines.  Artifacts
    predating the ``mode`` marker are synchronous by construction."""
    mode = art.get("mode") or (art.get("throughput") or {}).get("mode")
    return mode if mode == "streaming" else "synchronous"


def _solver_key(art: dict) -> str:
    """Solver-tier fingerprint for the comparability guard: a rung any
    of whose rounds the SHARDED tier served splits device work over a
    mesh, so its per-round count series (iterations, dispatches,
    per-shard lanes) are not commensurable with a single-chip rung's.
    Artifacts predating the ``solve_tiers`` field are single-chip by
    construction (the tier shipped with the field), so absence means
    "single"."""
    tiers = art.get("solve_tiers")
    if isinstance(tiers, (list, tuple)) and "sharded" in tiers:
        return "sharded"
    return "single"


def collect_timings(art: dict) -> Dict[str, float]:
    """Flatten an artifact's timing series to {dotted_name: seconds}.

    Only steady-state numbers: ``cold_s`` depends on compile-cache
    warmth (the artifact says so via ``cache_warm``) and is excluded —
    a cache-cold run must not fail the gate on compile time."""
    out: Dict[str, float] = {}
    for key in ("wave_p50_s", "churn_p50_s", "restart_s"):
        val = art.get(key)
        if isinstance(val, (int, float)):
            out[key] = float(val)
    features = art.get("features") or {}
    for config in ("selectors", "pod_affinity", "gang"):
        sub = features.get(config) or {}
        for stage in _FEATURE_STAGES:
            val = sub.get(stage)
            if isinstance(val, (int, float)):
                out[f"features.{config}.{stage}"] = float(val)
    return out


def collect_counts(art: dict) -> Dict[str, Tuple[float, float, float]]:
    """Device-work count series -> {name: (total, tolerance, floor)}.
    Series are per-round lists in the rung artifact; the gate compares
    their SUMS (per-round jitter is tie-break noise, the total is the
    device work the config paid)."""
    out: Dict[str, Tuple[float, float, float]] = {}
    for key, tol, floor in _COUNT_SERIES:
        val = art.get(key)
        if isinstance(val, list) and val and all(
            isinstance(v, (int, float)) for v in val
        ):
            out[f"device.{key}"] = (float(sum(val)), tol, float(floor))
    return out


# The machine-independent per-round device series printed (not gated)
# in the human-readable summary: an A/B session reads the deltas at a
# glance instead of digging both artifacts out of the gate's pass/fail.
_DEVICE_SERIES = (
    "wave_solve_iters", "wave_bf_sweeps", "wave_device_calls",
    "wave_entry_phase", "churn_solve_iters", "churn_device_calls",
)


def collect_device_series(art: dict) -> Dict[str, List[float]]:
    """The per-round device-work lists present in an artifact."""
    out: Dict[str, List[float]] = {}
    for key in _DEVICE_SERIES:
        val = art.get(key)
        if isinstance(val, list) and val and all(
            isinstance(v, (int, float)) for v in val
        ):
            out[key] = [float(v) for v in val]
    return out


def compare(
    baseline: dict,
    current: dict,
    tolerance: float = DEFAULT_TOLERANCE,
    abs_floor_s: float = DEFAULT_ABS_FLOOR_S,
) -> dict:
    """Pure comparison (tests pin this contract).  Returns::

        {"comparable": bool, "reason": str|None,
         "rows": [{"name", "baseline_s", "current_s", "ratio",
                   "verdict": "ok"|"regression"|"improved"}, ...],
         "skipped": [names missing on one side],
         "regressions": [names]}
    """
    base_key, cur_key = _config_key(baseline), _config_key(current)
    if base_key != cur_key:
        return {
            "comparable": False,
            "reason": (
                f"config mismatch: baseline {base_key} vs current "
                f"{cur_key} (backend/machines/tasks must match)"
            ),
            "rows": [], "skipped": [], "regressions": [],
        }
    base_mode, cur_mode = _mode_key(baseline), _mode_key(current)
    if base_mode != cur_mode:
        return {
            "comparable": False,
            "reason": (
                f"mode mismatch: baseline {base_mode} vs current "
                f"{cur_mode} — a streaming-engine artifact's throughput "
                "series measure a continuously-overlapped loop, "
                "apples-to-oranges against round-synchronous numbers"
            ),
            "rows": [], "skipped": [], "regressions": [],
        }
    base_solver, cur_solver = _solver_key(baseline), _solver_key(current)
    if base_solver != cur_solver:
        return {
            "comparable": False,
            "reason": (
                f"solver-tier mismatch: baseline {base_solver} vs "
                f"current {cur_solver} — a sharded-tier rung splits "
                "device work over a mesh, so its count series are "
                "apples-to-oranges against single-chip counts"
            ),
            "rows": [], "skipped": [], "regressions": [],
        }
    base_t, cur_t = collect_timings(baseline), collect_timings(current)
    rows, regressions = [], []
    skipped = sorted(set(base_t) ^ set(cur_t))
    for name in sorted(set(base_t) & set(cur_t)):
        b, c = base_t[name], cur_t[name]
        ratio = (c / b) if b > 0 else float("inf")
        verdict = "ok"
        if c > b * (1.0 + tolerance) and (c - b) > abs_floor_s:
            verdict = "regression"
            regressions.append(name)
        elif c < b * (1.0 - tolerance) and (b - c) > abs_floor_s:
            verdict = "improved"
        rows.append({
            "name": name, "baseline_s": b, "current_s": c,
            "ratio": round(ratio, 3), "verdict": verdict,
        })
    # Device-work count series: per-series tolerance/floor (the units
    # differ — iterations vs dispatches).  Same skip semantics as the
    # timing rows: a series present on one side only is reported.
    base_c, cur_c = collect_counts(baseline), collect_counts(current)
    skipped.extend(sorted(set(base_c) ^ set(cur_c)))
    for name in sorted(set(base_c) & set(cur_c)):
        b, tol, floor = base_c[name]
        c = cur_c[name][0]
        ratio = (c / b) if b > 0 else float("inf")
        verdict = "ok"
        if c > b * (1.0 + tol) and (c - b) > floor:
            verdict = "regression"
            regressions.append(name)
        elif c < b * max(1.0 - tol, 0.5) and (b - c) > floor:
            # Improvement band capped at halving: with tol >= 1 the
            # symmetric band would be negative and genuine wins (e.g.
            # every churn flip eliminated) would read as plain "ok".
            verdict = "improved"
        rows.append({
            "name": name, "baseline_s": b, "current_s": c,
            "ratio": round(ratio, 3), "verdict": verdict,
        })
    # Sustained throughput (streaming rung): direction is INVERTED —
    # placements/sec falling below the baseline's band is the
    # regression.  Both sides carry the same mode (the guard above), so
    # the number is commensurable when present on both.
    base_tp = (baseline.get("throughput") or {}).get("placements_per_sec")
    cur_tp = (current.get("throughput") or {}).get("placements_per_sec")
    if isinstance(base_tp, (int, float)) and isinstance(cur_tp, (int, float)):
        ratio = (cur_tp / base_tp) if base_tp > 0 else float("inf")
        verdict = "ok"
        if cur_tp < base_tp * (1.0 - tolerance):
            verdict = "regression"
            regressions.append("throughput.placements_per_sec")
        elif cur_tp > base_tp * (1.0 + tolerance):
            verdict = "improved"
        rows.append({
            "name": "throughput.placements_per_sec",
            "baseline_s": float(base_tp), "current_s": float(cur_tp),
            "ratio": round(ratio, 3), "verdict": verdict,
        })
    elif isinstance(base_tp, (int, float)) or isinstance(cur_tp, (int, float)):
        skipped.append("throughput.placements_per_sec")
    # Scenario rung series: per-scenario throughput and robustness are
    # INVERTED like the throughput series (falling below the band is
    # the regression); admission staleness gates in the normal
    # direction with the timing floor (it is a latency).  One-side-only
    # scenarios are skipped rows, so a fresh artifact diffs cleanly
    # against baselines predating the rung.
    base_sc = (baseline.get("scenario") or {}).get("scenarios") or {}
    cur_sc = (current.get("scenario") or {}).get("scenarios") or {}
    for sc in sorted(set(base_sc) ^ set(cur_sc)):
        skipped.append(f"scenario.{sc}")
    for sc in sorted(set(base_sc) & set(cur_sc)):
        b_e, c_e = base_sc[sc], cur_sc[sc]
        for key, inverted, floor in (
            ("placements_per_sec", True, 0.0),
            ("robustness_score", True, 0.0),
            ("admission_staleness_p50_s", False, abs_floor_s),
        ):
            b, c = b_e.get(key), c_e.get(key)
            name = f"scenario.{sc}.{key}"
            if not (isinstance(b, (int, float))
                    and isinstance(c, (int, float))):
                if isinstance(b, (int, float)) or isinstance(
                        c, (int, float)):
                    skipped.append(name)
                continue
            ratio = (c / b) if b > 0 else float("inf")
            verdict = "ok"
            if inverted:
                if c < b * (1.0 - tolerance):
                    verdict = "regression"
                    regressions.append(name)
                elif c > b * (1.0 + tolerance):
                    verdict = "improved"
            else:
                if c > b * (1.0 + tolerance) and (c - b) > floor:
                    verdict = "regression"
                    regressions.append(name)
                elif c < b * (1.0 - tolerance) and (b - c) > floor:
                    verdict = "improved"
            rows.append({
                "name": name, "baseline_s": float(b),
                "current_s": float(c),
                "ratio": round(ratio, 3), "verdict": verdict,
            })
    return {
        "comparable": True, "reason": None, "rows": rows,
        "skipped": sorted(skipped), "regressions": regressions,
        # Raw per-round device series (printed, not gated — the gate
        # already judges their SUMS above; the round-by-round shape is
        # what a live A/B session wants to eyeball).
        "device_series": {
            "baseline": collect_device_series(baseline),
            "current": collect_device_series(current),
        },
    }


def render(result: dict, baseline_path: str, current_path: str) -> str:
    lines = [f"perf-gate: {current_path} vs baseline {baseline_path}"]
    if not result["comparable"]:
        lines.append(f"  SKIP: {result['reason']}")
        return "\n".join(lines)
    width = max((len(r["name"]) for r in result["rows"]), default=4)
    lines.append(
        f"  {'series'.ljust(width)}  baseline_s  current_s   ratio  verdict"
    )
    for r in result["rows"]:
        lines.append(
            f"  {r['name'].ljust(width)}  {r['baseline_s']:10.4f}  "
            f"{r['current_s']:9.4f}  {r['ratio']:6.3f}  {r['verdict']}"
        )
    for name in result["skipped"]:
        lines.append(f"  {name.ljust(width)}  (present on one side only; "
                     "skipped)")
    # Per-round device-work series, human-readable (the PR 8 machine-
    # independent counts: solve_iters / bf_sweeps / device_calls /
    # entry_phase) — so an A/B session reads the round-by-round deltas
    # at a glance, not just the gated sums.
    ds = result.get("device_series") or {}
    base_s, cur_s = ds.get("baseline", {}), ds.get("current", {})
    names = sorted(set(base_s) | set(cur_s))
    if names:
        lines.append("  device series (per round, baseline -> current):")

        def fmt(vals):
            if vals is None:
                return "-"
            body = " ".join(
                str(int(v)) if float(v).is_integer() else f"{v:.3g}"
                for v in vals
            )
            return f"[{body}] sum={int(sum(vals))}"

        for name in names:
            lines.append(
                f"    {name}: {fmt(base_s.get(name))} -> "
                f"{fmt(cur_s.get(name))}"
            )
    n = len(result["regressions"])
    lines.append(
        f"  => {n} regression(s)" if n else "  => no regressions"
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--baseline", action="append", default=[],
                   help="baseline artifact path; repeatable — the first "
                        "parseable one wins (wrapper formats may be "
                        "truncated)")
    p.add_argument("--current", required=True,
                   help="fresh bench artifact (.json or .jsonl; last "
                        "parseable line wins)")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="allowed fractional slowdown before failing "
                        f"(default {DEFAULT_TOLERANCE})")
    p.add_argument("--abs-floor", type=float, default=DEFAULT_ABS_FLOOR_S,
                   help="minimum absolute slowdown in seconds to count "
                        f"(default {DEFAULT_ABS_FLOOR_S})")
    p.add_argument("--warn-only", action="store_true",
                   help="report regressions but always exit 0 (the "
                        "`make verify` wiring)")
    args = p.parse_args(argv)

    baselines = args.baseline or ["BENCH_r05.json",
                                  "docs/bench_r05_final.json"]
    baseline, baseline_path = first_artifact(baselines)
    current = load_artifact(args.current)
    if baseline is None or current is None:
        which = "baseline" if baseline is None else "current"
        missing = baselines if baseline is None else [args.current]
        print(f"perf-gate: no parseable {which} artifact in {missing}",
              file=sys.stderr)
        return 0 if args.warn_only else 2

    result = compare(baseline, current, tolerance=args.tolerance,
                     abs_floor_s=args.abs_floor)
    print(render(result, baseline_path, args.current))
    if result["regressions"] and not args.warn_only:
        return 1
    if result["regressions"]:
        print("perf-gate: WARN-ONLY mode; regressions above are not "
              "failing the build", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
