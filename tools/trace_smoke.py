"""Trace smoke (``make trace-smoke``): one features-config round with
tracing ON, exported and validated end to end.

Drives a small selector-config round (the BASELINE config-2 shape at
smoke scale) under ``POSEIDON_TRACE=1``, exports the Chrome trace-event
artifact to ``out/trace_smoke.json``, and fails unless:

- the export passes ``obs.trace.validate_chrome_trace`` (JSON-
  serializable, complete events, properly NESTED same-thread spans —
  the Perfetto-loadability contract);
- a ``round`` span exists and the stage spans
  (``round.mask_build`` / ``round.cost_build`` / ``round.solve_band`` /
  ``round.view_build``) are its children, contained in its interval;
- the span totals agree with ``stagetimer.snapshot()`` within 5%
  (tracer and stagetimer are two views of the same records — drift
  means the shim broke);
- a second, TWO-BAND traced round exercises the cross-band cost-build
  pipeline (graph/pipeline.py): a ``round.cost_build_spec`` span must
  land on a worker lane, cross-thread-parented to the round span, its
  interval overlapping the first band's ``round.solve_band`` — and the
  exported artifact (which now contains cross-LANE overlap) must still
  validate, proving the validator's lane-aware nesting rules;
- a third, CONTENDED round (bench.contended_cluster — demand past
  comfortable capacity, so the solve really iterates), exported to its
  OWN artifact (``out/trace_smoke_conv.json`` — the pipelined window 2
  keeps ``out/trace_smoke.json``), must render at least one ``conv.*``
  Perfetto COUNTER track: the solver's convergence-telemetry curves
  laid onto the timeline (obs/trace counter events, validated by
  ``validate_chrome_trace``).

CPU-pinned: a smoke gate must never contend for (or wedge on) the
accelerator tunnel.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STAGES = (
    "round.view_build", "round.mask_build", "round.cost_build",
    "round.solve_band",
)
PARITY_TOLERANCE = 0.05
OUT_PATH = os.path.join("out", "trace_smoke.json")
CONV_OUT_PATH = os.path.join("out", "trace_smoke_conv.json")


def validate_round_decomposition(spans, problems):
    """The round span must ancestor the stage spans (mask_build nests
    under cost_build — the cost model opens it), intervals contained."""
    rounds = [s for s in spans if s["name"] == "round"]
    if not rounds:
        problems.append("no 'round' span recorded")
        return
    rnd = rounds[-1]
    r0, r1 = rnd["ts"], rnd["ts"] + rnd["dur"]
    by_id = {s["id"]: s for s in spans}
    by_parent = {}
    for s in spans:
        by_parent.setdefault(s.get("parent"), []).append(s)

    def descends_from_round(span) -> bool:
        seen = set()
        parent = span.get("parent")
        while parent is not None and parent not in seen:
            if parent == rnd["id"]:
                return True
            seen.add(parent)
            parent = by_id.get(parent, {}).get("parent")
        return False

    for stage in STAGES:
        stage_spans = [s for s in spans if s["name"] == stage
                       and descends_from_round(s)]
        if not stage_spans:
            problems.append(
                f"stage span {stage!r} is not a descendant of the "
                "round span"
            )
            continue
        for s in stage_spans:
            if not (r0 <= s["ts"] and s["ts"] + s["dur"] <= r1 + 1e-9):
                problems.append(
                    f"stage span {stage!r} interval escapes its round span"
                )
    stage_sum = sum(
        s["dur"] for s in by_parent.get(rnd["id"], [])
        # Same-lane children only: a pipelined round's speculative cost
        # build runs CONCURRENTLY on a worker lane, so it legitimately
        # adds wall time beyond the round's own serial budget.
        if s["name"].startswith("round.")
        and s.get("tid") == rnd.get("tid")
    )
    if stage_sum > rnd["dur"] * 1.001:
        problems.append(
            f"stage spans sum to {stage_sum:.4f}s > round span "
            f"{rnd['dur']:.4f}s"
        )


def validate_stagetimer_parity(spans, snapshot, problems):
    from poseidon_tpu.obs.trace import span_totals

    totals = span_totals(spans)
    for stage in STAGES:
        span_s, span_n = totals.get(stage, (0.0, 0))
        timer_s, timer_n = snapshot.get(stage, (0.0, 0))
        if span_n != timer_n:
            problems.append(
                f"{stage}: {span_n} spans vs {timer_n} stagetimer calls"
            )
        ref = max(timer_s, 1e-9)
        if abs(span_s - timer_s) / ref > PARITY_TOLERANCE:
            problems.append(
                f"{stage}: span total {span_s:.4f}s vs stagetimer "
                f"{timer_s:.4f}s (> {PARITY_TOLERANCE:.0%} apart)"
            )


def validate_pipeline_overlap(spans, metrics, problems):
    """The pipelined round's contract: the speculative cost build ran on
    its own lane, parented to the round span across threads, and its
    interval actually overlapped a band solve."""
    rounds = [s for s in spans if s["name"] == "round"]
    specs = [s for s in spans if s["name"] == "round.cost_build_spec"]
    solves = [s for s in spans if s["name"] == "round.solve_band"]
    if not rounds or not specs or not solves:
        problems.append(
            "pipelined round: missing round/cost_build_spec/solve_band "
            f"spans ({len(rounds)}/{len(specs)}/{len(solves)})"
        )
        return
    rnd = rounds[-1]
    spec = specs[-1]
    if spec.get("tid") == rnd.get("tid"):
        problems.append(
            "cost_build_spec ran on the planner lane, not a worker lane"
        )
    if spec.get("parent") != rnd["id"]:
        problems.append(
            "cost_build_spec is not cross-thread-parented to the round"
        )
    s0, s1 = spec["ts"], spec["ts"] + spec["dur"]
    if not any(
        min(s1, sv["ts"] + sv["dur"]) > max(s0, sv["ts"])
        for sv in solves
    ):
        problems.append(
            "cost_build_spec interval overlaps no band solve — the "
            "pipeline submitted but never actually overlapped"
        )
    if not metrics.pipeline_overlap_s > 0:
        problems.append(
            f"pipeline_overlap_s={metrics.pipeline_overlap_s} — no "
            "realized overlap recorded in RoundMetrics"
        )


def _two_band_cluster():
    """~1200 machines, two size bands of 96 ECs each — big enough that
    band 2's speculative build is still running when band 1's solve
    starts (the overlap the pipelined round must realize)."""
    from poseidon_tpu.graph.state import ClusterState, MachineInfo, TaskInfo
    from poseidon_tpu.utils.ids import generate_uuid, task_uid

    state = ClusterState()
    for i in range(1200):
        state.node_added(MachineInfo(
            uuid=generate_uuid(f"ts2-m{i}"), cpu_capacity=32000,
            ram_capacity=128 << 20, task_slots=64,
        ))
    for necs, per_ec, cpu0 in ((96, 2, 100), (96, 32, 2000)):
        for e in range(necs):
            for i in range(per_ec):
                state.task_submitted(TaskInfo(
                    uid=task_uid(f"ts2-{cpu0}-{e}", i),
                    job_id=f"ts2-{cpu0}-{e}",
                    cpu_request=cpu0 + e, ram_request=1 << 19,
                ))
    return state


def main() -> int:
    # CPU pin BEFORE jax loads a backend (same recipe as tests/conftest:
    # env alone is too late when a site hook pre-registered a plugin).
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["POSEIDON_TRACE"] = "1"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from bench import build_cluster, contended_cluster, submit_population
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.obs import trace as obs_trace
    from poseidon_tpu.utils import stagetimer

    machines, tasks = 200, 1000
    state = build_cluster(machines, tasks, 16, seed=0)
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    planner.schedule_round()          # cold round: compiles land here
    obs_trace.reset()                 # a clean traced window
    submit_population(state, tasks // 10, 16, seed=1)
    _, metrics = planner.schedule_round()  # THE traced round

    spans = obs_trace.spans()
    snapshot = stagetimer.snapshot()
    obj = obs_trace.export_chrome_trace(OUT_PATH)

    problems = obs_trace.validate_chrome_trace(obj)
    validate_round_decomposition(spans, problems)
    validate_stagetimer_parity(spans, snapshot, problems)
    if not any(e.get("ph") == "X" and e["name"] == "round"
               for e in obj["traceEvents"]):
        problems.append("exported artifact has no 'round' event")

    # Window 2: the PIPELINED round (two band groups -> the speculative
    # cost build overlaps band 1's solve on a worker lane).  Exported
    # over the same artifact path so the committed smoke covers the
    # cross-lane-overlap shape the validator must accept.
    obs_trace.reset()
    state2 = _two_band_cluster()
    planner2 = RoundPlanner(state2, get_cost_model("cpu_mem"))
    _, metrics2 = planner2.schedule_round()
    spans2 = obs_trace.spans()
    obj2 = obs_trace.export_chrome_trace(OUT_PATH)
    problems += obs_trace.validate_chrome_trace(obj2)
    validate_round_decomposition(spans2, problems)
    validate_pipeline_overlap(spans2, metrics2, problems)

    # Window 3: a CONTENDED round (demand past comfortable capacity, so
    # the host certificate misses and the device ladder iterates) — the
    # convergence-telemetry curves must render as Perfetto counter
    # tracks next to the spans.  Exported to its OWN artifact: the
    # committed cross-lane-overlap artifact (OUT_PATH, window 2) must
    # survive for Perfetto inspection, not be overwritten here.
    obs_trace.reset()
    state3 = contended_cluster(prefix="ts3")
    planner3 = RoundPlanner(state3, get_cost_model("cpu_mem"))
    _, metrics3 = planner3.schedule_round()
    obj3 = obs_trace.export_chrome_trace(CONV_OUT_PATH)
    problems += obs_trace.validate_chrome_trace(obj3)
    conv_tracks = {
        k: v for k, v in obs_trace.counter_tracks(obj3).items()
        if k.startswith("conv.")
    }
    if metrics3.iterations == 0:
        problems.append(
            "contended window solved in 0 iterations — the counter-"
            "track assertion never exercised the telemetry path"
        )
    if not conv_tracks:
        problems.append(
            "no conv.* counter track rendered in the contended window "
            f"(iters={metrics3.iterations}, "
            f"telem_samples={metrics3.telem_samples})"
        )
    if metrics3.telem_samples and metrics3.iterations and \
            metrics3.telem_samples != sum(
                c["samples"] for c in planner3.last_solve_curves):
        problems.append("telem_samples disagrees with the curve digests")

    n_events = sum(1 for e in obj2["traceEvents"] if e.get("ph") == "X")
    print(f"trace-smoke: round solve_tier={metrics.solve_tier} "
          f"placed={metrics.placed}; {len(spans)} spans; pipelined "
          f"round overlap={metrics2.pipeline_overlap_s}s "
          f"delta_hits={metrics2.cost_delta_hits}, {n_events} events "
          f"-> {OUT_PATH}; contended round iters={metrics3.iterations}, "
          f"counter tracks {sorted(conv_tracks)} -> {CONV_OUT_PATH}")
    if problems:
        for prob in problems:
            print(f"trace-smoke: FAIL {prob}", file=sys.stderr)
        return 1
    print("trace-smoke: artifact valid (nesting incl. cross-lane "
          "pipeline overlap, counter tracks, Perfetto format, "
          "stagetimer parity)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
