"""Trace smoke (``make trace-smoke``): one features-config round with
tracing ON, exported and validated end to end.

Drives a small selector-config round (the BASELINE config-2 shape at
smoke scale) under ``POSEIDON_TRACE=1``, exports the Chrome trace-event
artifact to ``out/trace_smoke.json``, and fails unless:

- the export passes ``obs.trace.validate_chrome_trace`` (JSON-
  serializable, complete events, properly NESTED same-thread spans —
  the Perfetto-loadability contract);
- a ``round`` span exists and the stage spans
  (``round.mask_build`` / ``round.cost_build`` / ``round.solve_band`` /
  ``round.view_build``) are its children, contained in its interval;
- the span totals agree with ``stagetimer.snapshot()`` within 5%
  (tracer and stagetimer are two views of the same records — drift
  means the shim broke).

CPU-pinned: a smoke gate must never contend for (or wedge on) the
accelerator tunnel.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STAGES = (
    "round.view_build", "round.mask_build", "round.cost_build",
    "round.solve_band",
)
PARITY_TOLERANCE = 0.05
OUT_PATH = os.path.join("out", "trace_smoke.json")


def validate_round_decomposition(spans, problems):
    """The round span must ancestor the stage spans (mask_build nests
    under cost_build — the cost model opens it), intervals contained."""
    rounds = [s for s in spans if s["name"] == "round"]
    if not rounds:
        problems.append("no 'round' span recorded")
        return
    rnd = rounds[-1]
    r0, r1 = rnd["ts"], rnd["ts"] + rnd["dur"]
    by_id = {s["id"]: s for s in spans}
    by_parent = {}
    for s in spans:
        by_parent.setdefault(s.get("parent"), []).append(s)

    def descends_from_round(span) -> bool:
        seen = set()
        parent = span.get("parent")
        while parent is not None and parent not in seen:
            if parent == rnd["id"]:
                return True
            seen.add(parent)
            parent = by_id.get(parent, {}).get("parent")
        return False

    for stage in STAGES:
        stage_spans = [s for s in spans if s["name"] == stage
                       and descends_from_round(s)]
        if not stage_spans:
            problems.append(
                f"stage span {stage!r} is not a descendant of the "
                "round span"
            )
            continue
        for s in stage_spans:
            if not (r0 <= s["ts"] and s["ts"] + s["dur"] <= r1 + 1e-9):
                problems.append(
                    f"stage span {stage!r} interval escapes its round span"
                )
    stage_sum = sum(
        s["dur"] for s in by_parent.get(rnd["id"], [])
        if s["name"].startswith("round.")
    )
    if stage_sum > rnd["dur"] * 1.001:
        problems.append(
            f"stage spans sum to {stage_sum:.4f}s > round span "
            f"{rnd['dur']:.4f}s"
        )


def validate_stagetimer_parity(spans, snapshot, problems):
    from poseidon_tpu.obs.trace import span_totals

    totals = span_totals(spans)
    for stage in STAGES:
        span_s, span_n = totals.get(stage, (0.0, 0))
        timer_s, timer_n = snapshot.get(stage, (0.0, 0))
        if span_n != timer_n:
            problems.append(
                f"{stage}: {span_n} spans vs {timer_n} stagetimer calls"
            )
        ref = max(timer_s, 1e-9)
        if abs(span_s - timer_s) / ref > PARITY_TOLERANCE:
            problems.append(
                f"{stage}: span total {span_s:.4f}s vs stagetimer "
                f"{timer_s:.4f}s (> {PARITY_TOLERANCE:.0%} apart)"
            )


def main() -> int:
    # CPU pin BEFORE jax loads a backend (same recipe as tests/conftest:
    # env alone is too late when a site hook pre-registered a plugin).
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["POSEIDON_TRACE"] = "1"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from bench import build_cluster, submit_population
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.obs import trace as obs_trace
    from poseidon_tpu.utils import stagetimer

    machines, tasks = 200, 1000
    state = build_cluster(machines, tasks, 16, seed=0)
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    planner.schedule_round()          # cold round: compiles land here
    obs_trace.reset()                 # a clean traced window
    submit_population(state, tasks // 10, 16, seed=1)
    _, metrics = planner.schedule_round()  # THE traced round

    spans = obs_trace.spans()
    snapshot = stagetimer.snapshot()
    obj = obs_trace.export_chrome_trace(OUT_PATH)

    problems = obs_trace.validate_chrome_trace(obj)
    validate_round_decomposition(spans, problems)
    validate_stagetimer_parity(spans, snapshot, problems)
    if not any(e.get("ph") == "X" and e["name"] == "round"
               for e in obj["traceEvents"]):
        problems.append("exported artifact has no 'round' event")

    n_events = sum(1 for e in obj["traceEvents"] if e.get("ph") == "X")
    print(f"trace-smoke: round solve_tier={metrics.solve_tier} "
          f"placed={metrics.placed}; {len(spans)} spans, "
          f"{n_events} events -> {OUT_PATH}")
    if problems:
        for prob in problems:
            print(f"trace-smoke: FAIL {prob}", file=sys.stderr)
        return 1
    print("trace-smoke: artifact valid (nesting, Perfetto format, "
          "stagetimer parity)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
