"""A/B the fused Pallas ladder kernel against the lax path on the live
backend: correctness (bit-parity) first, then wall-clock at the churn-
and selective-representative shapes the fused kernel targets.

Usage (serialize against other chip users; never external-kill this):
    python tools/bench_fused.py [--reps 5]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_instance(E, M, seed, contended):
    from poseidon_tpu.ops.transport import INF_COST

    rng = np.random.default_rng(seed)
    costs = rng.integers(0, 1000, size=(E, M)).astype(np.int32)
    costs[rng.random((E, M)) < 0.05] = INF_COST
    supply = rng.integers(2, 12, size=E).astype(np.int32)
    if contended:
        capacity = np.zeros(M, np.int32)
        free = rng.choice(M, size=max(M // 2, 1), replace=False)
        capacity[free] = rng.integers(1, 4, size=free.size)
    else:
        capacity = rng.integers(1, 12, size=M).astype(np.int32)
    unsched = rng.integers(1000, 2000, size=E).astype(np.int32)
    return costs, supply, capacity, unsched


def run(mode, inst, reps):
    os.environ["POSEIDON_FUSED"] = mode
    from poseidon_tpu.ops.transport import solve_transport

    costs, supply, capacity, unsched = inst
    sol = solve_transport(costs, supply, capacity, unsched)  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        sol = solve_transport(costs, supply, capacity, unsched)
    return (time.perf_counter() - t0) / reps, sol


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()

    from poseidon_tpu.utils.envutil import (
        probe_device_count,
        serialize_device_access,
    )

    if not serialize_device_access():
        print("device lock busy; aborting", flush=True)
        raise SystemExit(2)
    if probe_device_count(timeout=300.0) < 0:
        print("backend unreachable; aborting", flush=True)
        raise SystemExit(2)

    import jax

    print(f"backend: {jax.devices()[0].platform}", flush=True)
    shapes = [
        (64, 512, False),    # small churn
        (128, 1024, True),   # selective width, contended
        (128, 2048, True),   # VMEM-budget edge
    ]
    if os.environ.get("POSEIDON_BENCH_FUSED_SMOKE"):
        # CPU smoke: interpret-mode Pallas is an emulator — keep it tiny.
        shapes = [(16, 128, False)]
    for E, M, cont in shapes:
        inst = make_instance(E, M, seed=7, contended=cont)
        t_lax, s_lax = run("0", inst, args.reps)
        t_fused, s_fused = run("1", inst, args.reps)
        from poseidon_tpu.ops import transport

        if transport._FUSED_BROKEN:
            # The whole point of this bench is Mosaic validation: a
            # silently-latched lax fallback must FAIL it, not produce a
            # 1.00x "pass" that never ran the kernel.
            print("FAIL: fused kernel did not lower on this backend "
                  "(fallback latched); see the log above", flush=True)
            raise SystemExit(1)
        ok = (
            s_lax.objective == s_fused.objective
            and s_lax.iterations == s_fused.iterations
            and np.array_equal(s_lax.flows, s_fused.flows)
            and np.array_equal(s_lax.prices, s_fused.prices)
        )
        print(
            f"[{E}x{M}{' cont' if cont else ''}] lax {t_lax * 1000:.1f}ms "
            f"fused {t_fused * 1000:.1f}ms speedup {t_lax / t_fused:.2f}x "
            f"iters={s_lax.iterations} bit-parity={'OK' if ok else 'FAIL'}",
            flush=True,
        )
        if not ok:
            raise SystemExit(1)


if __name__ == "__main__":
    main()
