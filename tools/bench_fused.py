"""A/B the Pallas kernels (fused ladder + tiled iteration) against the
lax path on the live backend: correctness (bit-parity) first, then
wall-clock at the shapes each kernel targets — churn/selective widths
for the fused ladder, wave widths for the tiled iteration kernel.

Usage (serialize against other chip users; never external-kill this):
    python tools/bench_fused.py [--reps 5]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from poseidon_tpu.utils.hatches import hatch_flag  # noqa: E402 - needs path


def make_instance(E, M, seed, contended):
    from poseidon_tpu.ops.transport import INF_COST

    rng = np.random.default_rng(seed)
    costs = rng.integers(0, 1000, size=(E, M)).astype(np.int32)
    costs[rng.random((E, M)) < 0.05] = INF_COST
    supply = rng.integers(2, 12, size=E).astype(np.int32)
    if contended:
        capacity = np.zeros(M, np.int32)
        free = rng.choice(M, size=max(M // 2, 1), replace=False)
        capacity[free] = rng.integers(1, 4, size=free.size)
    else:
        capacity = rng.integers(1, 12, size=M).astype(np.int32)
    unsched = rng.integers(1000, 2000, size=E).astype(np.int32)
    return costs, supply, capacity, unsched


def run(env_var, mode, inst, reps):
    os.environ["POSEIDON_FUSED"] = "0"
    os.environ["POSEIDON_TILED"] = "0"
    os.environ[env_var] = mode
    from poseidon_tpu.ops.transport import solve_transport

    costs, supply, capacity, unsched = inst
    sol = solve_transport(costs, supply, capacity, unsched)  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        sol = solve_transport(costs, supply, capacity, unsched)
    return (time.perf_counter() - t0) / reps, sol


def ab(kernel, env_var, latch, shapes, reps):
    """A/B one kernel over its shape list.  Returns the list of per-shape
    failure strings (empty == all green).  A failure on one shape must
    not abort the others — the round-5 live session lost the entire
    tiled-kernel verdict because a fused-shape VMEM OOM SystemExit'd the
    script before ``ab("tiled", ...)`` ever ran."""
    from poseidon_tpu.ops import transport
    from poseidon_tpu.ops.transport import padded_shape

    failures = []
    for E, M, cont in shapes:
        # The forced leg must actually ROUTE through the kernel: if the
        # shape gate declines (VMEM/tile budget), both legs run lax and
        # the "pass" is vacuous — fail the configuration instead.
        e_pad, m_pad = padded_shape(E, M)
        gate = (
            transport._use_fused if kernel == "fused"
            else transport._use_tiled
        )
        os.environ[env_var] = "1"
        if not gate(e_pad, m_pad):
            print(f"FAIL: {kernel} gate declines shape {E}x{M} "
                  f"(padded {e_pad}x{m_pad}); fix the shape list",
                  flush=True)
            failures.append(f"{kernel} {E}x{M}: gate declined")
            continue
        inst = make_instance(E, M, seed=7, contended=cont)
        t_lax, s_lax = run(env_var, "0", inst, reps)
        t_k, s_k = run(env_var, "1", inst, reps)
        if (e_pad, m_pad) in getattr(transport, latch):
            # The whole point is Mosaic validation: a silently-latched
            # lax fallback must FAIL, not report a 1.00x "pass".  The
            # latch is PER SHAPE — judge only this shape's entry, and
            # keep going so the remaining shapes still get verdicts.
            print(f"FAIL: {kernel} kernel did not lower for {E}x{M} "
                  "(fallback latched for this shape); see the log above",
                  flush=True)
            failures.append(f"{kernel} {E}x{M}: did not lower")
            continue
        ok = (
            s_lax.objective == s_k.objective
            and s_lax.iterations == s_k.iterations
            and np.array_equal(s_lax.flows, s_k.flows)
            and np.array_equal(s_lax.prices, s_k.prices)
        )
        print(
            f"[{kernel} {E}x{M}{' cont' if cont else ''}] "
            f"lax {t_lax * 1000:.1f}ms {kernel} {t_k * 1000:.1f}ms "
            f"speedup {t_lax / t_k:.2f}x iters={s_lax.iterations} "
            f"bit-parity={'OK' if ok else 'FAIL'}",
            flush=True,
        )
        if not ok:
            failures.append(f"{kernel} {E}x{M}: bit-parity mismatch")
    return failures


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()

    from poseidon_tpu.utils.envutil import (
        probe_device_count,
        serialize_device_access,
    )

    if not serialize_device_access():
        print("device lock busy; aborting", flush=True)
        raise SystemExit(2)
    if probe_device_count(timeout=300.0) < 0:
        print("backend unreachable; aborting", flush=True)
        raise SystemExit(2)

    import jax

    print(f"backend: {jax.devices()[0].platform}", flush=True)
    fused_shapes = [
        (64, 512, False),    # small churn
        (128, 1024, True),   # selective width, contended
        (128, 1280, True),   # VMEM-budget edge (163840 elems == budget)
    ]
    tiled_shapes = [
        (128, 4096, False),  # above VMEM: the wave tier
        (128, 10000, True),  # the 10k-machine wave shape, contended
    ]
    if hatch_flag("POSEIDON_BENCH_FUSED_SMOKE"):
        # CPU smoke: interpret-mode Pallas is an emulator — keep it tiny.
        fused_shapes = [(16, 128, False)]
        tiled_shapes = []
    failures = ab("fused", "POSEIDON_FUSED", "_FUSED_BROKEN",
                  fused_shapes, args.reps)
    failures += ab("tiled", "POSEIDON_TILED", "_TILED_BROKEN",
                   tiled_shapes, args.reps)
    if failures:
        print("VERDICT: FAIL — " + "; ".join(failures), flush=True)
        raise SystemExit(1)
    print("VERDICT: PASS — all shapes lowered with bit-parity", flush=True)


if __name__ == "__main__":
    main()
