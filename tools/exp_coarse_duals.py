"""Experiment: coarse machine-axis warm start for fresh-wave solves.

Round-4 verdict item 6: the ~550-iteration fresh-wave solve at 10k/100k
is the scale-invariant term no lever has dented.  Hypothesis: solve a
COLUMN-AGGREGATED instance first (machines grouped into K supernodes of
similar cost columns, capacities summed), lift its exact duals (and
optionally a disaggregated primal) onto the full instance, and start the
epsilon ladder at the lift's certified violation instead of the cold
eps0.  The aggregated solve is cheap ([E, K] with K << M) and its duals
carry the load-shaped equilibrium structure the greedy+alternation cold
start cannot express under contention.

Measures, per captured fresh-wave band solve:
  baseline   — the production cold start (greedy flows + auction duals);
  coarse-A   — coarse duals + greedy flows;
  coarse-B   — coarse duals + disaggregated coarse flows.
All three must reach the identical objective (the solver is exact).
Results recorded in docs/PERF.md either way (positive or negative).
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import bench as B  # noqa: E402
from poseidon_tpu.costmodel import get_cost_model  # noqa: E402
from poseidon_tpu.graph.instance import RoundPlanner  # noqa: E402
from poseidon_tpu.ops import transport as T  # noqa: E402


def capture_wave_instances(machines, tasks, ecs):
    """One warm-cache wave round; returns the cold band instances."""
    captured = []
    orig = RoundPlanner._dispatch_solve

    def spy(self, costs, supply, capacity, unsched_cost, prices=None, **kw):
        sol = orig(self, costs, supply, capacity, unsched_cost, prices,
                   **kw)
        captured.append(dict(
            costs=np.asarray(costs).copy(),
            supply=np.asarray(supply).copy(),
            capacity=np.asarray(capacity).copy(),
            unsched=np.asarray(unsched_cost).copy(),
            arc=(None if kw.get("arc_capacity") is None
                 else np.asarray(kw["arc_capacity"]).copy()),
            warm=prices is not None,
            iters=sol.iterations,
            objective=sol.objective,
        ))
        return sol

    RoundPlanner._dispatch_solve = spy
    try:
        state = B.build_cluster(machines, tasks, ecs, seed=0)
        planner = RoundPlanner(state, get_cost_model("cpu_mem"))
        planner.schedule_round()  # cold round (compiles; not measured)
        for uid in list(state.tasks.keys()):
            state.task_removed(uid)
        B.submit_population(state, tasks, ecs, seed=1)
        captured.clear()
        t0 = time.perf_counter()
        _, m = planner.schedule_round()
        wall = time.perf_counter() - t0
    finally:
        RoundPlanner._dispatch_solve = orig
    print(f"# wave round: {wall:.2f}s iters={m.iterations} "
          f"calls={len(captured)} objective={m.objective}")
    return [c for c in captured if not c["warm"]]


def group_columns(costs, K):
    """Group machine columns into K supernodes of similar cost columns.

    Sort by admissible column mean (the cpu_mem cost is ~load(m) +
    request-shaped terms, so the mean captures the load axis) and chunk
    into equal-count groups; columns with identical admissibility
    patterns and nearby means land together.
    """
    E, M = costs.shape
    adm = costs < T.INF_COST
    colmean = np.where(adm, costs, 0).sum(axis=0) / np.maximum(
        adm.sum(axis=0), 1
    )
    # Dead columns (no admissible rows) to the end, one group of junk.
    dead = ~adm.any(axis=0)
    order = np.lexsort((colmean, dead))
    gid = np.empty(M, dtype=np.int64)
    bounds = np.linspace(0, M, K + 1).astype(int)
    for g in range(K):
        gid[order[bounds[g]:bounds[g + 1]]] = g
    return gid


def aggregate(costs, capacity, arc, gid, K):
    E, M = costs.shape
    adm = costs < T.INF_COST
    Cg = np.full((E, K), T.INF_COST, dtype=np.int32)
    capg = np.zeros(K, dtype=np.int64)
    arcg = np.zeros((E, K), dtype=np.int64)
    arc64 = (arc.astype(np.int64) if arc is not None
             else np.full((E, M), T.UNBOUNDED_ARC_CAP, dtype=np.int64))
    arc64 = np.where(adm, arc64, 0)
    for g in range(K):
        mask = gid == g
        capg[g] = capacity.astype(np.int64)[mask].sum()
        a = adm[:, mask]
        any_adm = a.any(axis=1)
        c = np.where(a, costs[:, mask], 0).sum(axis=1) / np.maximum(
            a.sum(axis=1), 1
        )
        Cg[any_adm, g] = np.round(c[any_adm]).astype(np.int32)
        arcg[:, g] = arc64[:, mask].sum(axis=1)
    capg = np.minimum(capg, np.iinfo(np.int32).max // 4).astype(np.int32)
    arcg = np.minimum(arcg, np.iinfo(np.int32).max // 4).astype(np.int32)
    return Cg, capg, arcg


def disaggregate(flows_g, costs, capacity, arc, gid, K):
    """Distribute each (row, group) flow onto the group's member columns,
    cheapest member first, respecting column and arc capacities."""
    E, M = costs.shape
    adm = costs < T.INF_COST
    flows = np.zeros((E, M), dtype=np.int32)
    col_left = capacity.astype(np.int64).copy()
    arc64 = (arc.astype(np.int64) if arc is not None
             else np.full((E, M), T.UNBOUNDED_ARC_CAP, dtype=np.int64))
    members = [np.nonzero(gid == g)[0] for g in range(K)]
    for g in range(K):
        ms = members[g]
        rows = np.nonzero(flows_g[:, g] > 0)[0]
        for e in rows.tolist():
            want = int(flows_g[e, g])
            order = ms[np.argsort(costs[e, ms], kind="stable")]
            for mcol in order.tolist():
                if want == 0:
                    break
                if not adm[e, mcol]:
                    continue
                u = int(min(want, col_left[mcol], arc64[e, mcol]))
                if u > 0:
                    flows[e, mcol] += u
                    col_left[mcol] -= u
                    want -= u
            # Undistributable remainder (arc caps tighter after
            # averaging): drop to unscheduled-side; the ladder fixes it.
    return flows


def run_variant(name, inst, scale, init_prices=None, init_flows=None,
                init_unsched=None, eps_start=None, greedy_init=True):
    t0 = time.perf_counter()
    sol = T.solve_transport(
        inst["costs"], inst["supply"], inst["capacity"], inst["unsched"],
        init_prices, arc_capacity=inst["arc"], init_flows=init_flows,
        init_unsched=init_unsched, eps_start=eps_start, scale=scale,
        greedy_init=greedy_init,
    )
    dt = time.perf_counter() - t0
    print(f"  {name:10s} iters={sol.iterations:5d} wall={dt:6.2f}s "
          f"obj={sol.objective} gap={sol.gap_bound}")
    return sol


def experiment(inst, K):
    costs, supply = inst["costs"], inst["supply"]
    E, M = costs.shape
    if M < 4 * K or supply.sum() < 1000:
        return  # churn-sized; not the target case
    print(f"# instance [E={E}, M={M}] supply={int(supply.sum())} "
          f"(production iters={inst['iters']})")
    e_pad, m_pad = T.padded_shape(E, M)
    scale, _ = T.derive_scale(costs, inst["unsched"], None, e_pad, m_pad)

    base = run_variant("baseline", inst, scale)

    t0 = time.perf_counter()
    gid = group_columns(costs, K)
    Cg, capg, arcg = aggregate(costs, inst["capacity"], inst["arc"],
                               gid, K)
    coarse = T.solve_transport(
        Cg, supply, capg, inst["unsched"], arc_capacity=arcg, scale=scale,
    )
    t_coarse = time.perf_counter() - t0
    pe = coarse.prices[:E]
    pm = coarse.prices[E:E + K][gid]
    pt = coarse.prices[E + K]
    lifted = np.concatenate([pe, pm, [pt]]).astype(np.int32)
    print(f"  coarse [{E}x{K}] iters={coarse.iterations} "
          f"wall={t_coarse:.2f}s obj={coarse.objective}")

    # A: coarse duals + fresh greedy flows at those duals.
    gf = T.greedy_flows(costs, supply, inst["capacity"], inst["arc"])
    left = (supply.astype(np.int64) - gf.sum(axis=1)).astype(np.int32)
    eps_a = T._certified_eps(
        gf, left, lifted, costs=costs, supply=supply,
        capacity=inst["capacity"], unsched_cost=inst["unsched"],
        scale=scale, arc_capacity=inst["arc"],
    )
    print(f"  eps_A={eps_a} (cold eps0 ~ {scale * int(np.where(costs < T.INF_COST, costs, 0).max()) // 2})")
    a = run_variant("coarse-A", inst, scale, lifted, gf, left, eps_a,
                    greedy_init=False)

    # B: coarse duals + disaggregated coarse primal.
    t0 = time.perf_counter()
    df = disaggregate(coarse.flows, costs, inst["capacity"], inst["arc"],
                      gid, K)
    left_b = (supply.astype(np.int64) - df.sum(axis=1)).astype(np.int32)
    eps_b = T._certified_eps(
        df, left_b, lifted, costs=costs, supply=supply,
        capacity=inst["capacity"], unsched_cost=inst["unsched"],
        scale=scale, arc_capacity=inst["arc"],
    )
    print(f"  eps_B={eps_b} disagg={time.perf_counter() - t0:.2f}s")
    b = run_variant("coarse-B", inst, scale, lifted, df, left_b, eps_b,
                    greedy_init=False)

    for sol, nm in ((a, "A"), (b, "B")):
        if sol.objective != base.objective:
            print(f"  !! objective mismatch {nm}: {sol.objective} "
                  f"vs {base.objective}")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--machines", type=int, default=2000)
    p.add_argument("--tasks", type=int, default=20000)
    p.add_argument("--ecs", type=int, default=100)
    p.add_argument("--groups", type=int, default=256)
    args = p.parse_args()
    insts = capture_wave_instances(args.machines, args.tasks, args.ecs)
    for inst in insts:
        experiment(inst, args.groups)


if __name__ == "__main__":
    main()
