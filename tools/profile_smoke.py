"""Profile smoke (``make profile-smoke``): the solver-introspection
layer end to end on one CPU-pinned process.

Drives a contended round with convergence telemetry ON and fails
unless:

- the round captured per-band convergence curves: RoundMetrics carries
  the roll-ups (``telem_samples`` / ``telem_iters_to_90``), the curve
  digests are JSON-safe with monotone iteration indices and a
  non-increasing tail, and the artifact lands in
  ``out/profile_smoke.json``;
- the hatch-gated ``jax.profiler.trace`` window captured an XLA
  profile under ``out/profile_smoke_jax/round_*`` (POSEIDON_JAX_PROFILE
  wired through ``obs/profile.solve_profile``);
- a live ``MetricsServer`` answers the introspection endpoints:
  ``/debug/rounds`` lists the recorded rounds, ``/debug/round/<n>``
  returns the full record with curves, ``/healthz`` reports JSON
  liveness with a last-round age;
- a WARM instrumented round holds BOTH ``CompileLedger(budget=0)`` and
  ``TransferLedger(budget=0)`` — the telemetry ring rides the existing
  single host_fetch batch, so instrumentation adds zero fresh compiles
  and zero extra transfer slots to the steady state.

CPU-pinned: a smoke gate must never contend for (or wedge on) the
accelerator tunnel.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT_PATH = os.path.join("out", "profile_smoke.json")
PROFILE_DIR = os.path.join("out", "profile_smoke_jax")


def _validate_curves(curves, problems):
    for c in curves:
        try:
            json.dumps(c)
        except (TypeError, ValueError) as e:
            problems.append(f"curve digest not JSON-safe: {e}")
            continue
        if c["samples"] <= 0:
            problems.append(f"band {c.get('band')}: empty curve digest")
            continue
        iters = c["iters"]
        if any(b <= a for a, b in zip(iters, iters[1:])):
            problems.append(
                f"band {c.get('band')}: iteration indices not "
                f"strictly increasing: {iters[:8]}..."
            )
        if any(v < 0 for v in c["active_excess"]):
            problems.append(
                f"band {c.get('band')}: negative active excess"
            )
        if c["iters_to_90"] < 0 or c["decay_half_life"] < 0:
            problems.append(
                f"band {c.get('band')}: negative drain/half-life"
            )


def main() -> int:
    # CPU pin BEFORE jax loads a backend (same recipe as trace_smoke).
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["POSEIDON_SOLVE_TELEMETRY"] = "1"
    shutil.rmtree(PROFILE_DIR, ignore_errors=True)
    os.environ["POSEIDON_JAX_PROFILE"] = PROFILE_DIR
    import jax

    jax.config.update("jax_platforms", "cpu")

    from bench import contended_cluster
    from poseidon_tpu.check.ledger import CompileLedger, TransferLedger
    from poseidon_tpu.costmodel import get_cost_model
    from poseidon_tpu.graph.instance import RoundPlanner
    from poseidon_tpu.obs import metrics as obs_metrics
    from poseidon_tpu.obs.history import default_history

    problems: list = []
    default_history().clear()

    # Shared contention recipe (bench.contended_cluster): the solve
    # cannot host-certify, so the telemetry ring captures a curve.
    state = contended_cluster(prefix="ps")
    planner = RoundPlanner(state, get_cost_model("cpu_mem"))
    _, m_cold = planner.schedule_round()   # cold: compiles land here
    if m_cold.iterations == 0:
        problems.append("contended cold round solved in 0 iterations — "
                        "nothing exercised the telemetry ring")
    if m_cold.telem_samples == 0:
        problems.append("cold round captured no telemetry samples "
                        f"(iters={m_cold.iterations})")
    curves = list(planner.last_solve_curves)
    _validate_curves(curves, problems)

    # jax profiler capture: the solve window of the cold round should
    # have produced an artifact directory with at least one file.
    cap_dir = os.path.join(PROFILE_DIR, f"round_{m_cold.round_index:06d}")
    captured = [
        os.path.join(r, f)
        for r, _, fs in os.walk(cap_dir) for f in fs
    ]
    if not captured:
        problems.append(
            f"no jax profiler artifact under {cap_dir} "
            "(POSEIDON_JAX_PROFILE window never captured)"
        )

    # Warm instrumented round under BOTH budget-0 ledgers: re-place a
    # slice of the population (same shapes -> same compile keys) so the
    # round does real work without minting compiles, and the telemetry
    # fetch must add no transfer slots.
    uids = sorted(state.tasks.keys())[: len(state.tasks) // 10]
    from poseidon_tpu.graph.state import TaskInfo
    from poseidon_tpu.utils.ids import task_uid

    for uid in uids:
        state.task_removed(uid)
    for i, _uid in enumerate(uids):
        state.task_submitted(TaskInfo(
            uid=task_uid("ps-warm", i), job_id="ps-0",
            cpu_request=300, ram_request=1 << 18,
        ))
    with CompileLedger(budget=0, label="profile-smoke warm round"), \
            TransferLedger(budget=0, label="profile-smoke warm round"):
        _, m_warm = planner.schedule_round()

    # Introspection endpoints on a live exporter (the planner recorded
    # both rounds into the default history ring).
    server = obs_metrics.MetricsServer("127.0.0.1:0").start()
    try:
        base = f"http://{server.address}"
        with urllib.request.urlopen(f"{base}/debug/rounds", timeout=5) as r:
            listing = json.loads(r.read())
        rounds = [s["round"] for s in listing["rounds"]]
        if m_cold.round_index not in rounds or \
                m_warm.round_index not in rounds:
            problems.append(
                f"/debug/rounds missing recorded rounds: got {rounds}"
            )
        url = f"{base}/debug/round/{m_cold.round_index}"
        with urllib.request.urlopen(url, timeout=5) as r:
            rec = json.loads(r.read())
        if len(rec.get("curves", [])) != len(curves):
            problems.append(
                f"/debug/round/{m_cold.round_index} carries "
                f"{len(rec.get('curves', []))} curves, planner produced "
                f"{len(curves)}"
            )
        if rec["metrics"].get("telem_samples") != m_cold.telem_samples:
            problems.append("/debug round record disagrees with "
                            "RoundMetrics.telem_samples")
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            health = json.loads(r.read())
        if not health.get("ok") or health.get("last_round_age_s") is None:
            problems.append(f"/healthz liveness report wrong: {health}")
    finally:
        server.stop()

    os.makedirs("out", exist_ok=True)
    with open(OUT_PATH, "w", encoding="utf-8") as fh:
        json.dump({
            "cold": m_cold.to_dict(),
            "warm": m_warm.to_dict(),
            "curves": curves,
            "profiler_files": len(captured),
        }, fh)
        fh.write("\n")

    print(f"profile-smoke: cold iters={m_cold.iterations} "
          f"samples={m_cold.telem_samples} "
          f"iters_to_90={m_cold.telem_iters_to_90} "
          f"half_life={m_cold.telem_decay_half_life} "
          f"curves={len(curves)}; warm iters={m_warm.iterations} "
          f"(budget-0 ledgers held); profiler files={len(captured)} "
          f"-> {OUT_PATH}")
    if problems:
        for prob in problems:
            print(f"profile-smoke: FAIL {prob}", file=sys.stderr)
        return 1
    print("profile-smoke: telemetry curves valid, /debug + /healthz "
          "served, CompileLedger+TransferLedger budget-0 held warm")
    return 0


if __name__ == "__main__":
    sys.exit(main())
